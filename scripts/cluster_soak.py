#!/usr/bin/env python3
"""Cluster-in-a-box: the end-to-end placement-quality harness
(ISSUE 14, ROADMAP open item #5 / BASELINE multi-slice target #5).

Every prior soak proves one layer in isolation (fleet sink, slice
coherence, plugin containment, aggregator rollups); THIS one proves the
product: that the published google.com/tpu.* labels make placement
measurably better under failure. It composes the existing simulation
pieces on ONE seeded virtual clock:

  N slices x M hosts of sim daemons   — per-host ground truth (perf
      class, wedge, partition, preemption, daemon death) detected at
      probe cadence and published as NodeFeature labels;
  per-slice coordination               — a leader merges member reports
      into an agreed verdict (healthy-hosts / degraded / class = worst
      member), republished by every live member; leader death fails
      over at lease expiry; a partitioned member CANNOT write its own
      demotion (the PR 12 tradeoff), so its object holds stale-good
      labels until heal;
  the sharded sim apiserver            — SSA writes, collection watch
      fan-out, write brownouts (server-alive pacing: publishes defer
      and retry, reports do NOT age out — the PR 9 orphan rule);
  the parity-pinned SimAggregator      — tpufd.agg rollups feeding the
      scheduler's capacity-by-class admission gate;
  the label-driven toy scheduler       — tpufd.cluster.SimScheduler,
      which sees ONLY published labels (never sim ground truth) and
      places a synthetic job stream.

A seeded failure schedule (tpufd.cluster grammar; see
docs/placement-harness.md) drives chip degradation, host wedges, slice
partitions, preemption notices, leader kills, and apiserver brownouts
while the harness measures the headline numbers:

  label-to-placement latency  — ground-truth event -> the scheduler's
      placeable() verdict for the victim flips (it stops landing jobs
      there); p99 gated absolutely and vs BENCH_cluster.json;
  jobs landed on bad hardware — placements onto ground-truth-bad hosts
      AFTER the per-failure-class convergence window: must be ZERO
      (inside the window is physics — labels propagate at probe +
      agreement + publish cadence — and is recorded, not gated);
  recovery time               — heal event -> placeable() again, plus
      the first job actually landing back;
  decisions under fire        — placement decisions served per second
      during the dense failure storm, and the fraction that landed on
      good hardware.

Determinism is an acceptance invariant: the whole simulation is run
TWICE with the same seed and the two records must serialize
byte-identically (no wall clock, no ambient randomness, sorted
iteration everywhere); bench_gate.py --cluster gates the committed
BENCH_cluster.json on all of the above.

A SECOND mode (ISSUE 17) rides the same virtual clock: `--shards N
--placement-qps Q` runs the sharded aggregation tree + placement query
service at fleet scale (default 100k nodes) — N L1 InventoryStore twins
each owning 1/N of the fleet by name hash, publishing partial rollups
over the pinned wire format; one L2 ShardMergeStore root merging them
O(delta) into the cluster inventory; and the tpufd.placement index fed
by the same label stream answering a seeded query mix. It measures
inventory staleness (churn -> merged root publish), per-tier flush QPS,
and REAL placements/sec served correctly (wall clock around the query
calls only — everything else stays virtual), proves the merged root
byte-identical to a flat single-store oracle through a shard
retire/re-admit drill, and double-runs the seed for byte determinism.
bench_gate --shard gates the committed BENCH_shard.json.

Usage:
  python3 scripts/cluster_soak.py [--slices 12] [--hosts 4] [--seed 14]
      [--json out] [--quick] [--schedule FILE] [--once]
  python3 scripts/cluster_soak.py --shards 8 --placement-qps 2000
      [--nodes 100000] [--churn-rate 200] [--json out] [--quick]
"""

import argparse
import hashlib
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from tpufd import agg as agglib  # noqa: E402
from tpufd import cluster as clusterlib  # noqa: E402
from tpufd import placement as placementlib  # noqa: E402
from tpufd import remedy as remedylib  # noqa: E402
from tpufd import sink as sinklib  # noqa: E402
from tpufd.fakes.simnet import (  # noqa: E402
    SimAggregator, SimClock, percentile)

PREFIX = "google.com/"

# Per-failure-class convergence windows (seconds): the label pipeline's
# worst-case detection + agreement + publish budget for each class.
# Placements onto the victim INSIDE the window are excused (recorded as
# bad_placements_within_window); one placement AFTER it is a gate
# failure. The budget arithmetic lives in docs/placement-harness.md.
CONVERGENCE_WINDOW_S = {
    "degrade": 1.5,    # event-driven probe + publish + wire
    "preempt": 1.5,    # metadata fast path + publish + wire
    "wedge": 3.0,      # peer probe confirms stale at agreement/2 + pub
    "partition": 4.0,  # confirm + pre-declared succession (no full
                       # lease-expiry wait: ISSUE 19)
}
# A brownout no longer freezes label flow outright — the leader hedges
# and the store sheds (admits a fraction of) paced writes — but tails
# stretch; failures overlapping one get their window extended past the
# brownout's end by this much.
BROWNOUT_GRACE_S = 2.0

PROBE_INTERVAL_S = 1.0
AGREEMENT_S = 2.0
LEASE_S = 3.0
# Peer report relay (ISSUE 19): a member whose blackboard report went
# stale past agreement/2 is probed directly by its peers; a failed
# probe CONFIRMS the staleness and the merge excludes the member now
# instead of waiting out the full ageing window.
RELAY_CONFIRM_S = AGREEMENT_S / 2.0
# Pre-declared lease succession (ISSUE 19): the verdict names the
# successor line; the first live successor promotes at the first
# missed renewal tick (renew cadence lease/3 = 1s, missed at 1.5x)
# instead of full lease expiry at 3s.
SUCCESSION_S = LEASE_S / 3.0 * 1.5
# Brownout shedding: a browned-out apiserver paces writers but still
# ADMITS this fraction of attempts (Retry-After is per-request, not a
# blackout) — the reason a verdict can reach the scheduler through a
# racing member's publish while the others back off.
BROWNOUT_ACCEPT_P = 0.55
AGG_DEBOUNCE_S = 1.0
AGG_LEASE_S = 30.0
JOB_FAIL_DETECT_S = 1.0

# Fleet SLO engine (ISSUE 16), time-compressed for the virtual clock:
# the node's 10-minute sketch window becomes 15 virtual seconds, the
# burn evaluator's 5m/1h fast/slow windows become 5s/20s. Same
# arithmetic (tpufd.agg.BurnEvaluator takes the windows as
# parameters), ~40x compression so a soak covers fold -> burn ->
# retire -> clear end to end.
SLO_WINDOW_S = 15.0
SLO_FAST_WINDOW_S = 5.0
SLO_SLOW_WINDOW_S = 20.0
SLO_BURN_TICK_S = 0.5   # the runner's flush-loop evaluation cadence
SLO_NODE_TICK_S = 1.0   # each daemon's retire-oldest sweep

# Placement explainability (ISSUE 18): which rejection-taxonomy reasons
# each injected failure class may legitimately produce on its victim
# AFTER the convergence window. degrade demotes the published class
# (degraded outright, or below a job's floor); preempt/preempt-clear
# ride the lifecycle labels; wedge/partition victims cannot publish
# their own demotion, so the only label evidence is a peer's
# degraded-slice verdict. A post-window rejection of a ground-truth-bad
# node carrying a reason OUTSIDE its failure's class is an attribution
# fidelity miss — bench_gate --explain requires zero.
EXPLAIN_REASON_CLASSES = {
    "degrade": {"perf-degraded", "class-floor", "slice-member-degraded"},
    "preempt": {"lifecycle-preempt", "lifecycle-draining"},
    "wedge": {"slice-member-degraded"},
    "partition": {"slice-member-degraded"},
}


def usec(t):
    """Virtual seconds -> integer microseconds. Queue-wait attribution
    quantizes TIMESTAMPS (not intervals) so per-interval attributions
    telescope exactly: sum(q(t[i+1]) - q(t[i])) == q(t[n]) - q(t[0])
    over integers — the reason histogram sums to the measured wait
    EXACTLY, not within epsilon."""
    return int(round(t * 1e6))


# ---- the apiserver, as the cluster sees it --------------------------------


class ClusterApiServer:
    """Sharded store + collection-watch fan-out to MANY watchers (the
    aggregator and the scheduler), plus write brownouts. Also speaks
    the AggSimServer surface (objects / count_agg / watcher /
    output_writes) so the stock SimAggregator runs against it."""

    def __init__(self, clock, rng, shards):
        self.clock = clock
        self.rng = rng
        self.shards = shards
        self.objects = {}          # node -> labels
        self.watchers = []         # objects with .on_event(t, node, labels)
        # Causal-trace hooks (set by run_sim once the topology exists):
        # the store stamps the "publish" stage for every open change of
        # the writing host's slice — the sim analogue of the daemon's
        # write-acked trace stamp.
        self.tracker = None
        self.hosts_by_name = {}
        self.by_verb = {}
        self.shard_buckets = {}    # (shard, sec) -> writes
        self.brownout_until = 0.0
        self.brownout_rejected = 0
        self.slowdown_until = 0.0
        self.slowdown_delay_s = 0.0
        self.slowdown_stretched = 0
        self.agg_requests = {}     # int(t) -> n (SimAggregator surface)
        self.output_writes = []    # (t, labels) rollup applies

    def _wire_latency(self):
        return self.rng.uniform(0.0005, 0.003)

    def shard_of(self, name):
        return sinklib.fnv1a64(name) % self.shards

    def _count(self, t, verb, name=None):
        self.by_verb[verb] = self.by_verb.get(verb, 0) + 1
        if name is not None:
            key = (self.shard_of(name), int(t))
            self.shard_buckets[key] = self.shard_buckets.get(key, 0) + 1

    def count_agg(self, t, verb):
        self.agg_requests[int(t)] = self.agg_requests.get(int(t), 0) + 1
        self._count(t, verb)

    @property
    def watcher(self):
        return None

    @watcher.setter
    def watcher(self, w):
        # SimAggregator.sync() assigns server.watcher = self; here that
        # ENROLLS it next to the scheduler instead of replacing it.
        self.add_watcher(w)

    def add_watcher(self, w):
        if w not in self.watchers:
            self.watchers.append(w)

    def brownout(self, t, secs):
        self.brownout_until = max(self.brownout_until, t + secs)

    def brownout_active(self, t):
        return t < self.brownout_until

    def slowdown(self, t, secs, delay_s):
        """The SLO engine's latency-regression drill: for `secs`, every
        publish attempt lands ~delay_s late (a tail-latency regression,
        NOT an outage — watches, reads, and the aggregator's rollup
        writes are unaffected, so the burn verdict can still publish
        WHILE the regression is in flight)."""
        self.slowdown_until = max(self.slowdown_until, t + secs)
        self.slowdown_delay_s = delay_s

    def slowdown_active(self, t):
        return t < self.slowdown_until

    def daemon_apply(self, t, node, labels):
        """A daemon's SSA write: store + watch fan-out. Brownout pacing
        is the CALLER's contract, not this method's — SimHost._publish
        rolls the shedding lottery (BROWNOUT_ACCEPT_P) and schedules its
        own retry on a reject (keeping the publish_pending slot so later
        dirtying events ride it), so a write that reaches here always
        lands. A silent drop here would lose the host's labels with no
        retry and no watch event — exactly the stale-store lie the
        harness exists to catch."""
        self._count(t, "APPLY", node)
        self.objects[node] = dict(labels)
        host = self.hosts_by_name.get(node)
        if host is not None:
            # The harness's exact-value mirror of the SLO annotation
            # that just landed: the fold multiset resident in this
            # host's sketches at apply time (the fleet-vs-harness
            # checkpoint compares against the merged fleet view, which
            # lags this by one wire hop).
            host.published_slo_folds = list(host.slo_folds)
        if self.tracker is not None and host is not None:
            for m in host.slice.members:
                self.tracker.stamp_node(m.name, "publish", t)
        for w in self.watchers:
            self.clock.schedule(
                t + self._wire_latency(),
                lambda now, w=w, n=node, lb=dict(labels):
                    w.on_event(now, n, lb))


class ClusterAggregator(SimAggregator):
    """The stock SimAggregator plus inventory delivery: every rollup
    apply is fanned out to the scheduler (one more collection watcher,
    watching the output object) after wire latency."""

    def __init__(self, server, clock, debounce_s, lease_s, deliver,
                 tracker):
        super().__init__(server, clock, debounce_s, lease_s)
        self.deliver = deliver
        self.tracker = tracker
        # Change ids seen in consumed node events, awaiting a rollup
        # publish: cid -> (first-seen t, op). Resolved (and echoed onto
        # the delivered inventory, the sim's annotation) at flush time —
        # the agg-debounce channel of the stage breakdown.
        self.pending_change_ids = {}
        self.agg_latency_ms_by_op = {}
        # Fleet SLO engine (ISSUE 16): multi-window burn evaluation over
        # the merged per-stage sketches, on the flush-loop cadence the
        # real runner uses (the sim compresses the windows, not the
        # arithmetic).
        self.burn = agglib.BurnEvaluator(
            agglib.slo_budgets_ms_from_spec(""),
            fast_window_s=SLO_FAST_WINDOW_S,
            slow_window_s=SLO_SLOW_WINDOW_S)
        self.burn_edges = []        # {"t", "stage", "burning"}
        self.burn_label_flushes = 0

    def _stage_slo(self, labels):
        # The annotation analogue: serialized stage sketches ride the
        # object next to the change id (tpufd.cluster.SLO_KEY).
        return (labels or {}).get(clusterlib.SLO_KEY, "")

    def sync(self, t):
        super().sync(t)
        self.clock.schedule(t + SLO_BURN_TICK_S,
                            lambda now: self._burn_tick(now))

    def _burn_tick(self, now):
        for stage, burning in self.burn.note(now, self.store.stage):
            self.burn_edges.append({"t": round(now, 3), "stage": stage,
                                    "burning": burning})
            # A verdict edge is a label movement: it rides the very
            # flush it dirties (the runner evaluates before the flush
            # decision for the same reason).
            self._note_dirty(now)
        self.clock.schedule(now + SLO_BURN_TICK_S,
                            lambda t: self._burn_tick(t))

    def on_event(self, t, node, labels):
        if labels and self.tracker is not None:
            cid = labels.get(clusterlib.CHANGE_KEY, "")
            if cid.isdigit():
                record = self.tracker.records.get(int(cid))
                if record is not None and \
                        int(cid) not in self.pending_change_ids:
                    self.pending_change_ids[int(cid)] = (t, record["op"])
        super().on_event(t, node, labels)

    def _flush(self, t):
        if self.server.brownout_active(t) and \
                self.server.rng.random() >= BROWNOUT_ACCEPT_P:
            # The rollup APPLY is a write like any other: a browned-out
            # server sheds it with Retry-After (admitting only the
            # BROWNOUT_ACCEPT_P fraction), so the inventory channel
            # slows during a brownout exactly like the per-node labels
            # do. Keep the flush slot (flush_scheduled stays True,
            # later dirtying events ride this retry) and retry at the
            # server's pacing cadence.
            self.server.brownout_rejected += 1
            self.clock.schedule(t + self.server.rng.uniform(0.2, 0.35),
                                lambda now: self._flush(now))
            return
        before = len(self.server.output_writes)
        super()._flush(t)
        if len(self.server.output_writes) > before:
            _, labels = self.server.output_writes[-1]
            # Burn verdict labels ride the rollup exactly like the real
            # runner's output: one row per currently-burning stage.
            burning = self.burn.burning_stages()
            for stage in burning:
                labels[agglib.SLO_BURN_PREFIX + stage + ".burn"] = "true"
            if burning:
                self.burn_label_flushes += 1
            delivered = dict(labels)
            if self.pending_change_ids:
                # Echo the latest change id this rollup folded in (the
                # inventory object's annotation in the real runner) and
                # score the agg-debounce channel: node-event seen ->
                # rollup delivered.
                delivered[clusterlib.CHANGE_KEY] = str(
                    max(self.pending_change_ids))
                for cid in sorted(self.pending_change_ids):
                    seen_t, op = self.pending_change_ids[cid]
                    self.agg_latency_ms_by_op.setdefault(op, []).append(
                        (t - seen_t) * 1000.0)
                self.pending_change_ids = {}
            self.clock.schedule(
                t + self.server._wire_latency(),
                lambda now, lb=delivered: self.deliver(now, lb))


# ---- hosts + slices (the simulated daemons) -------------------------------


class SimHost:
    """One host's daemon: ground truth on the left, published labels on
    the right, a probe/publish pipeline in between. The scheduler NEVER
    sees the gt_* fields — only what publish() lands in the store."""

    def __init__(self, server, clock, rng, slice_ref, member_idx):
        self.server = server
        self.clock = clock
        self.rng = rng
        self.slice = slice_ref
        self.tracker = slice_ref.tracker
        self.member_idx = member_idx
        self.name = f"sim-s{slice_ref.idx:02d}-h{member_idx:02d}"
        self.chips = 8
        self.base_class = "gold" if rng.random() < 0.7 else "silver"
        self.gt_degraded = False
        self.gt_wedged = False
        self.gt_partitioned = False
        self.gt_asym = False     # severed from the apiserver ONLY
        self.gt_preempting = False
        self.gt_alive = True
        self.publish_pending = False
        # Windowed stage-SLO sketches (obs/slo.h StageSlo analogue):
        # closed causal chains fold here; folds older than SLO_WINDOW_S
        # retire on the node tick and the shrunken serialization rides
        # the next publish.
        self.slo_folds = []      # (fold t, slo stage, ms)
        self.slo_sketches = {}   # slo stage -> agglib.Sketch
        self.slo_tick_live = False
        # The harness mirrors every fold for the fleet-vs-harness
        # checkpoint cross-check (run_sim wires this): stretched-ack
        # folds originate HERE, not in a closed chain, so the mirror
        # must hang off the fold itself.
        self.on_fold = None      # callable(now, stage_ms) or None
        # Snapshot of slo_folds as of the last store-applied publish
        # (ClusterApiServer.daemon_apply captures it): the exact-value
        # twin of the serialized annotation the fleet merge consumed.
        self.published_slo_folds = []

    def api_reachable(self):
        """Can this daemon talk to the apiserver / blackboard?
        (A brownout is NOT unreachability: server-alive pacing.)"""
        return self.gt_alive and not self.gt_wedged and \
            not self.gt_partitioned and not self.gt_asym

    def peer_reachable(self):
        """Can this daemon's PEERS reach its introspection endpoint?
        An asymmetric partition (gt_asym) severs only the apiserver
        path — peers still fetch its live report and relay it (ISSUE
        19), so the slice verdict keeps counting it healthy."""
        return self.gt_alive and not self.gt_wedged and \
            not self.gt_partitioned

    def gt_bad(self):
        """Is the HARDWARE unusable for a job right now? (A dead daemon
        with healthy chips is not bad hardware — leader-kill drills the
        label layer, not the silicon. Likewise an asym-partitioned
        member: its chips are fine and its labels keep flowing via the
        leader's hedged publish.)"""
        return (self.gt_degraded or self.gt_wedged or
                self.gt_partitioned or self.gt_preempting)

    def effective_class(self):
        return "degraded" if self.gt_degraded else self.base_class

    def desired_labels(self):
        v = self.slice.adopted_verdict
        labels = {
            PREFIX + "tfd.node": self.name,
            PREFIX + "tpu.count": str(self.chips),
            PREFIX + "tpu.accelerator-type": "v5litepod-32",
            PREFIX + "tpu.perf.class": self.effective_class(),
            clusterlib.SLICE_ID: self.slice.slice_id,
            clusterlib.SLICE_DEGRADED:
                "true" if v["degraded"] else "false",
            clusterlib.SLICE_CLASS: v["class"],
            clusterlib.SLICE_HEALTHY_HOSTS: str(v["healthy_hosts"]),
        }
        if self.gt_preempting:
            labels[clusterlib.LIFECYCLE_PREEMPT] = "true"
        # The change-id annotation analogue: the latest open change any
        # slice member is carrying rides every member's publish (the
        # verdict moves every member's labels; the annotation is how
        # the scheduler-side join proves the propagation).
        open_ids = [self.tracker.open_change(m.name)
                    for m in self.slice.members]
        open_ids = [i for i in open_ids if i is not None]
        if open_ids:
            labels[clusterlib.CHANGE_KEY] = str(max(open_ids))
        # The stage-slo annotation analogue: the current windowed
        # sketches, serialized exactly like the real daemon's
        # tfd.google.com/stage-slo (empty sketches write nothing).
        if self.slo_sketches:
            labels[clusterlib.SLO_KEY] = \
                agglib.serialize_stage_sketches(self.slo_sketches)
        return labels

    def mark_dirty(self, t):
        """Something this daemon publishes changed: render + write after
        a short detection/render latency. Coalesces like the real
        pass loop — one in-flight publish at a time. An asym-severed
        member cannot write itself, but its peers still see it: the
        slice leader proxies the publish (ISSUE 19 write hedging)."""
        if self.publish_pending:
            return
        if not self.api_reachable():
            if self.peer_reachable():
                self.slice.hedge_publish(t, self)
            return
        self.publish_pending = True
        self.clock.schedule(t + self.rng.uniform(0.05, 0.2),
                            lambda now: self._publish(now))

    def _publish(self, now):
        if not self.publish_pending:
            return  # a hedge landed this and handed the slot back
        if not self.api_reachable():
            self.publish_pending = False  # re-marked on heal (or hedged)
            if self.peer_reachable():
                self.slice.hedge_publish(now, self)
            return
        # First attempt closes the "hold" stage for every open slice
        # change (render/coalesce is done); a brownout deferral from
        # here on is "publish" time — first-wins stamps keep the retry
        # from moving the mark.
        for m in self.slice.members:
            self.tracker.stamp_node(m.name, "hold", now)
        if self.server.brownout_active(now) and \
                self.rng.random() >= BROWNOUT_ACCEPT_P:
            # Server-directed shedding: this attempt drew Retry-After.
            # Retry at the server's pacing cadence, keep the pending
            # slot so later dirtying events ride this retry. The slice
            # verdict still converges through whichever racing member
            # draws an admit first (placeability is worst-of-members).
            self.server.brownout_rejected += 1
            self.clock.schedule(now + self.rng.uniform(0.2, 0.35),
                                lambda t: self._publish(t))
            return
        if self.server.slowdown_active(now):
            # The latency-regression drill: the write itself lands, but
            # its ACK comes back ~delay_s late — a tail-latency
            # regression on the write path, not an outage. The daemon's
            # SLO sketches fold the OBSERVED attempt->ack duration when
            # the ack arrives; the label flow itself is not delayed
            # (watch fan-out fires on the store apply, not the ack).
            stretch = self.server.slowdown_delay_s * \
                self.rng.uniform(0.8, 1.2)
            self.server.slowdown_stretched += 1
            self.clock.schedule(
                now + stretch,
                lambda t, ms=stretch * 1000.0: self.fold_slo(
                    t, {"publish": ms, "publish-acked": ms}))
        self.publish_pending = False
        self.server.daemon_apply(now, self.name, self.desired_labels())

    # ---- the windowed stage-SLO fold (obs/slo.h analogue) -----------------

    def fold_slo(self, now, stage_ms):
        """One closed causal chain's durations, mapped onto the node
        SLO stages, fold into this daemon's windowed sketches; the
        updated serialization rides the next publish."""
        for stage in sorted(stage_ms):
            self.slo_folds.append((now, stage, stage_ms[stage]))
            self.slo_sketches.setdefault(
                stage, agglib.Sketch()).add(stage_ms[stage])
        if self.on_fold is not None:
            self.on_fold(now, stage_ms)
        self.mark_dirty(now)
        if not self.slo_tick_live:
            self.slo_tick_live = True
            self.clock.schedule(now + SLO_NODE_TICK_S,
                                lambda t: self._slo_tick(t))

    def _slo_tick(self, now):
        """Retire-oldest: folds past the window leave the sketches
        (exact removal — the sketch is removable by design) and the
        shrunken view republishes, which is what lets the fleet burn
        verdict CLEAR after a regression heals."""
        cutoff = now - SLO_WINDOW_S
        expired = [f for f in self.slo_folds if f[0] <= cutoff]
        if expired:
            self.slo_folds = [f for f in self.slo_folds
                              if f[0] > cutoff]
            for _, stage, ms in expired:
                sketch = self.slo_sketches.get(stage)
                if sketch is not None:
                    sketch.remove(ms)
                    if sketch.total <= 0:
                        del self.slo_sketches[stage]
            self.mark_dirty(now)
        if self.slo_folds:
            self.clock.schedule(now + SLO_NODE_TICK_S,
                                lambda t: self._slo_tick(t))
        else:
            self.slo_tick_live = False

    # ---- ground-truth injections (the schedule's ops) ---------------------

    def probe_detect(self, t):
        """A ground-truth change this daemon can SELF-detect (perf skew,
        preemption notice): rides the device-event/lifecycle fast path
        (a watch on the metadata server + the PJRT health callback),
        so it lands well inside the probe round, then reports to the
        slice leader and republishes."""
        delay = self.rng.uniform(0.1, 0.55 * PROBE_INTERVAL_S)
        self.clock.schedule(t + delay, self._detected)

    def _detected(self, now):
        if not self.gt_alive:
            return
        self.tracker.stamp_node(self.name, "detect", now)
        self.mark_dirty(now)
        self.slice.on_report(now, self)


class SimSlice:
    """Per-slice coordination: a lease-elected leader merges member
    reports into the adopted verdict; every live member republishes the
    agreed labels. Mirrors the PR 9/12 protocol shape (agreement
    timeout for stale reports, lease failover, preempting member ->
    proactive degraded) plus the ISSUE 19 partition-tolerance upgrades
    (peer report relay with confirmed-stale exclusion, pre-declared
    succession at the first missed renewal, hedged publishes) at
    simulation fidelity."""

    def __init__(self, server, clock, rng, idx, host_count, tracker):
        self.server = server
        self.clock = clock
        self.rng = rng
        self.idx = idx
        self.tracker = tracker
        self.slice_id = f"slice-{idx:04d}"
        self.members = [SimHost(server, clock, rng, self, h)
                        for h in range(host_count)]
        self.leader_idx = 0
        self.failover_pending = False
        self.leader_transitions = 0
        self.relayed_reports = 0
        self.successions = 0
        self.hedged_publishes = 0
        self.adopted_verdict = self._compute_verdict()

    def leader(self):
        return self.members[self.leader_idx]

    def _compute_verdict(self):
        healthy = 0
        worst_rank = 99
        worst = "gold"
        for m in self.members:
            # Peer-reachable is what the MERGED view sees: a member
            # severed only from the apiserver still counts, because a
            # peer relays its live report onto the blackboard
            # (--slice-relay). Only a member no peer can reach ages out.
            if not m.peer_reachable():
                continue
            if not m.api_reachable():
                self.relayed_reports += 1
            rank = clusterlib.CLASS_RANK.get(m.effective_class(), 0)
            if rank < worst_rank:
                worst_rank, worst = rank, m.effective_class()
            if not m.gt_degraded and not m.gt_preempting:
                healthy += 1
        return {
            "healthy_hosts": healthy,
            # A missing/degraded/preempting member degrades the whole
            # slice verdict: multi-host workloads need every host, and
            # a preemption notice is a PROACTIVE demotion (PR 12).
            "degraded": healthy < len(self.members),
            "class": worst if worst_rank < 99 else "degraded",
        }

    def on_report(self, t, _member):
        """A fresh member report landed on the blackboard: the leader
        folds it on its next coordination tick."""
        self.clock.schedule(t + self.rng.uniform(0.1, 0.3),
                            lambda now: self.recompute(now))

    def on_member_unreachable(self, t):
        """A member stopped refreshing its report (wedge / partition /
        death): its report goes stale at agreement/2, a peer's direct
        probe FAILS, and the confirmed-stale exclusion drops it from
        the merge now (ISSUE 19) — no waiting out the full ageing
        window. Fresh-reported members are never probed."""
        def confirmed(now):
            # The failed relay probe IS the detection for a member that
            # cannot self-report: the "detect" stage of a
            # wedge/partition chain ends here (stale-after + one probe
            # is its budget).
            for m in self.members:
                if not m.peer_reachable():
                    self.tracker.stamp_node(m.name, "detect", now)
            self.recompute(now)
        self.clock.schedule(
            t + RELAY_CONFIRM_S + self.rng.uniform(0.05, 0.18),
            confirmed)
        if not self.leader().api_reachable():
            self._schedule_failover(t)

    def _schedule_failover(self, t):
        """Pre-declared succession (ISSUE 19): the adopted verdict
        already names the successor line, so the first-listed live
        follower promotes at the first MISSED RENEWAL TICK
        (SUCCESSION_S), epoch-fenced like any acquisition — full lease
        expiry stays the backstop only when no successor survives."""
        if self.failover_pending:
            return
        self.failover_pending = True
        self.clock.schedule(
            t + SUCCESSION_S + self.rng.uniform(0.02, 0.12),
            lambda now: self._failover(now))

    def _failover(self, now):
        self.failover_pending = False
        if self.leader().api_reachable():
            return  # old leader healed inside its lease: no transition
        for idx, m in enumerate(self.members):
            if m.api_reachable():
                self.leader_idx = idx
                self.leader_transitions += 1
                self.successions += 1
                self.recompute(now)
                return
        # Nobody api-reachable (full-slice partition): the next heal's
        # report path re-triggers election via on_report/recompute.
        self._schedule_failover(now)

    def hedge_publish(self, t, member):
        """Write hedging (ISSUE 19): the leader proxies a severed
        member's publish under the hedge field manager. Coalesces
        newest-wins on the member's own pending slot — the same slot
        its own pass loop uses, so on heal the member reclaims
        ownership with no duplicate stream."""
        leader = self.leader()
        if leader is member or not leader.api_reachable():
            return
        if member.publish_pending:
            return
        member.publish_pending = True
        self.clock.schedule(t + self.rng.uniform(0.1, 0.3),
                            lambda now: self._hedge_land(now, member))

    def _hedge_land(self, now, member):
        if not member.publish_pending:
            return
        if member.api_reachable():
            # Healed while the hedge was in flight: hand the slot back
            # to the member's own pass loop (SSA ownership reclaim).
            member.publish_pending = False
            member.mark_dirty(now)
            return
        leader = self.leader()
        if leader is member or not leader.api_reachable():
            member.publish_pending = False
            return
        if self.server.brownout_active(now) and \
                self.rng.random() >= BROWNOUT_ACCEPT_P:
            self.server.brownout_rejected += 1
            self.clock.schedule(now + self.rng.uniform(0.2, 0.35),
                                lambda t: self._hedge_land(t, member))
            return
        member.publish_pending = False
        self.hedged_publishes += 1
        self.server.daemon_apply(now, member.name,
                                 member.desired_labels())

    def recompute(self, now):
        if not self.leader().api_reachable():
            self._schedule_failover(now)
            return
        verdict = self._compute_verdict()
        if verdict == self.adopted_verdict:
            return
        self.adopted_verdict = verdict
        # The adopted verdict now reflects every open change on this
        # slice's members: the "agree" stage ends (for a leader-death
        # window this lands after the missed-renewal succession, which
        # is exactly the budget the partition class pays).
        for m in self.members:
            self.tracker.stamp_node(m.name, "agree", now)
        # Every live member republishes the agreed labels (small skew:
        # the members' own pass loops); an asym-severed member's copy
        # routes through the leader's hedge inside mark_dirty.
        for m in self.members:
            if m.peer_reachable():
                m.mark_dirty(now + self.rng.uniform(0.0, 0.3))


# ---- failure schedules ----------------------------------------------------


def default_schedule_text(slices, hosts):
    """The full seeded chaos timeline: one serialized drill per failure
    class, then a dense storm, then staggered heal-all. Written in the
    tpufd.cluster grammar so the soak exercises the same parser the
    docs teach. Needs >= 8 slices x >= 4 hosts."""
    if slices < 8 or hosts < 4:
        raise ValueError("full schedule wants >= 8 slices x >= 4 hosts "
                         "(use --quick below that)")
    return f"""\
# phase A — one drill per failure class, serialized
20   degrade        s0/h1
30   heal           s0/h1
# the ISSUE 19 asym drill: s6/h1 loses the apiserver but not its
# peers; the degrade on s6/h3 inside the window forces a verdict
# change the leader must HEDGE onto s6/h1's labels. The assertion is
# the non-event: no flap, no spurious demotion of s6/h1.
21   asym-partition s6/h1
23   degrade        s6/h3
27   heal           s6/h3
31   asym-heal      s6/h1
24   preempt        s1/h2
34   preempt-clear  s1/h2
28   wedge          s2/h0
40   unwedge        s2/h0
36   leader-kill    s3
48   leader-restart s3
44   partition      s4 hosts=0-1
58   heal-partition s4
52   brownout       apiserver secs=5
# phase B — the storm: every class at once, then staggered heals
62   degrade        s5/h3
62.4 degrade        s6/h0
62.8 wedge          s7/h1
63.2 preempt        s0/h3
63.6 partition      s1 hosts=0-1
64   leader-kill    s2
66   brownout       apiserver secs=4
68   degrade        s3/h2
78   heal           s5/h3
79   heal           s6/h0
80   unwedge        s7/h1
81   preempt-clear  s0/h3
82   heal-partition s1
83   leader-restart s2
84   heal           s3/h2
# phase C — the SLO regression drill (ISSUE 16): a stretched-publish
# window with serialized failures inside it; the burn verdict must
# assert while the stretch is live and clear once the folds retire
90   slowdown       apiserver secs=16 delay=3
92   degrade        s4/h0
94   degrade        s5/h1
96   degrade        s6/h2
98   degrade        s7/h3
108  heal           s4/h0
109  heal           s5/h1
110  heal           s6/h2
111  heal           s7/h3
"""


def quick_schedule_text(slices, hosts):
    """Compressed drill set for the CI smoke: every op class once on a
    4-slice topology, no long storm. Needs >= 4 slices x >= 3 hosts."""
    if slices < 4 or hosts < 3:
        raise ValueError("quick schedule wants >= 4 slices x >= 3 hosts")
    return """\
10 degrade        s0/h1
18 heal           s0/h1
11 asym-partition s0/h2
22 asym-heal      s0/h2
12 wedge          s1/h0
22 unwedge        s1/h0
14 preempt        s2/h1
20 preempt-clear  s2/h1
16 leader-kill    s3
26 leader-restart s3
24 partition      s0 hosts=0-1
32 heal-partition s0
28 brownout       apiserver secs=3
36 slowdown       apiserver secs=10 delay=3
37 degrade        s1/h1
39 degrade        s2/h0
52 heal           s1/h1
53 heal           s2/h0
"""


# Failures closer together than this are one storm burst; the
# decisions-under-fire metrics cover the LARGEST such burst, not the
# whole chaos timeline — averaging the calm, serialized phase-A drills
# into the storm numbers would dilute a regression that only shows
# when failure classes overlap.
STORM_GAP_S = 3.0


def storm_window(events):
    """The dense-failure window the decisions-under-fire metrics cover:
    the largest burst of failures with consecutive gaps <= STORM_GAP_S
    (ties -> the later burst), through the last heal at or after the
    burst starts — the storm isn't over until its victims healed."""
    fails = sorted(e.at for e in events
                   if e.op in ("degrade", "wedge", "preempt", "partition",
                               "leader-kill", "brownout"))
    heals = [e.at for e in events
             if e.op in ("heal", "unwedge", "preempt-clear",
                         "heal-partition", "leader-restart")]
    if not fails or not heals:
        return (0.0, 0.0)
    bursts = [[fails[0]]]
    for at in fails[1:]:
        if at - bursts[-1][-1] <= STORM_GAP_S:
            bursts[-1].append(at)
        else:
            bursts.append([at])
    burst = max(bursts, key=lambda b: (len(b), b[0]))
    tail = [at for at in heals if at >= burst[0]]
    return (burst[0], max(tail)) if tail else (0.0, 0.0)


# ---- the harness ----------------------------------------------------------


class Harness:
    """Owns the job stream, the ground-truth-vs-placement scoring, and
    the latency trackers. The ONLY component allowed to look at both
    sides (ground truth and labels) — the scheduler sees labels only."""

    def __init__(self, clock, rng, sched, hosts_by_name, arrival_dt,
                 tracker,
                 job_classes=("any", "silver", "any", "gold", "silver")):
        self.clock = clock
        self.rng = rng
        self.sched = sched
        self.hosts = hosts_by_name
        self.arrival_dt = arrival_dt
        self.changes = tracker
        self.job_classes = job_classes
        self.queue = []            # FIFO of Job
        self.jobs = {}             # job_id -> Job
        self.attempt = {}          # job_id -> placement generation
        self.next_job = 0
        self.drain_scheduled = False
        # scoring
        self.placement_log = []    # (t, job_id, node, gt_bad, excused)
        self.excused_until = {}    # node -> t
        self.down_track = {}       # node -> (t0, op)
        self.up_track = {}         # node -> (t0, op)
        self.latency_ms_by_op = {}
        self.recovery_s_by_op = {}
        self.land_after_heal = {}  # node -> heal t0 (first-landing watch)
        self.first_land_s = []
        self.bad_within = 0
        self.bad_after = 0
        self.violations = []
        self.jobs_submitted = 0
        self.jobs_completed = 0
        self.jobs_failed_bad_hw = 0
        self.jobs_requeued = 0
        self.inventory_updates = 0
        self.sched_events = 0
        # Fleet SLO engine (ISSUE 16): the harness's own copy of every
        # fold (ground truth for the fleet-vs-harness cross-check) and
        # the checkpoint snapshot taken after the regression drill.
        self.slo_folds = []        # (t, slo stage, ms)
        self.slo_checkpoint = None
        # Placement explainability (ISSUE 18): queue-wait attribution
        # (every queued microsecond lands in exactly one reason bucket)
        # and the reason-class fidelity scorer.
        self.active_fail_ops = {}  # node -> set of live injected ops
        self.enqueue_us = {}       # job_id -> µs of last (re)enqueue
        self.wait_mark_us = {}     # job_id -> µs of last attribution
        self.span_attr_us = {}     # job_id -> {reason: µs} (open span)
        self.job_wait_us = {}      # job_id -> measured wait µs (closed)
        self.job_attr_us = {}      # job_id -> {reason: µs} (closed)
        self.explain_checked = 0
        self.explain_mismatched = 0
        self.explain_by_op = {}    # op -> {"checked","mismatched"}
        self.explain_mismatches = []  # examples, <= 5

    # ---- label-side hooks (wired as watch delivery) -----------------------

    def on_label_event(self, now, node, labels):
        self.sched_events += 1
        # The change-id join: a delivery carrying a known change id
        # proves the annotation propagated daemon -> apiserver ->
        # scheduler; the "fanout" stage ends for every open change of
        # the publishing host's slice.
        cid = (labels or {}).get(clusterlib.CHANGE_KEY, "")
        if cid.isdigit() and int(cid) < self.changes.next_change:
            self.changes.label_events_joined += 1
        host = self.hosts.get(node)
        if host is not None:
            for m in host.slice.members:
                self.changes.stamp_node(m.name, "fanout", now)
        self.sched.on_event(node, labels)
        self._after_view_change(now)

    def on_inventory(self, now, labels):
        self.inventory_updates += 1
        cid = (labels or {}).get(clusterlib.CHANGE_KEY, "")
        if cid.isdigit() and int(cid) < self.changes.next_change:
            self.changes.inventory_joined += 1
        self.sched.on_inventory(labels)
        self._schedule_drain(now)

    def _after_view_change(self, now):
        # Resolve latency trackers: a tracked-down node the scheduler
        # now refuses = the label pipeline delivered; a tracked-up node
        # it accepts again = recovery. One blocked-set scan covers
        # every tracked node against this view.
        blocked = clusterlib.slice_blocked_ids(self.sched.view)
        for node in sorted(self.down_track):
            if not self.sched.placeable(node, blocked):
                t0, op = self.down_track.pop(node)
                self.latency_ms_by_op.setdefault(op, []).append(
                    (now - t0) * 1000.0)
                # Close the causal chain at the SAME moment the
                # end-to-end latency resolves: the stage durations
                # partition exactly this number.
                closed = self.changes.close(node, now)
                # The fold: the victim's daemon sketches its own closed
                # chain (the sim analogue of MarkPublished feeding
                # StageSlo) and the harness keeps the exact values the
                # fleet rollup must reproduce within sketch error.
                if closed is not None:
                    stage_ms = clusterlib.slo_stage_durations(
                        closed["stages"])
                    host = self.hosts.get(node)
                    if host is not None:
                        # The host's on_fold hook mirrors the fold into
                        # self.slo_folds — one shared path with the
                        # stretched-ack folds, so the fleet-vs-harness
                        # checkpoint counts stay exactly equal.
                        host.fold_slo(now, stage_ms)
        for node in sorted(self.up_track):
            if self.sched.placeable(node, blocked):
                t0, op = self.up_track.pop(node)
                self.recovery_s_by_op.setdefault(op, []).append(now - t0)
                self.land_after_heal[node] = t0
        # Label-driven eviction (preempt drain, slice demotion): jobs on
        # now-unplaceable nodes re-queue.
        for job_id in self.sched.drain_ineligible(now):
            self._requeue(job_id, now)
        self._schedule_drain(now)

    # ---- the job stream ---------------------------------------------------

    def start_arrivals(self, t0, t_end):
        t = t0
        i = 0
        while t < t_end:
            self.clock.schedule(t, lambda now: self._arrive(now))
            i += 1
            t = t0 + i * self.arrival_dt

    def _arrive(self, now):
        job_id = f"job-{self.next_job:05d}"
        wanted = self.job_classes[self.next_job % len(self.job_classes)]
        self.next_job += 1
        job = clusterlib.Job(job_id, wanted, chips=4,
                             duration_s=self.rng.uniform(2.0, 5.0))
        self.jobs[job_id] = job
        self.jobs_submitted += 1
        self.queue.append(job)
        self.enqueue_us[job_id] = self.wait_mark_us[job_id] = usec(now)
        self._schedule_drain(now)

    def _requeue(self, job_id, now):
        job = self.jobs.get(job_id)
        if job is None:
            return
        self.attempt[job_id] = self.attempt.get(job_id, 0) + 1
        self.jobs_requeued += 1
        self.queue.append(job)
        self.enqueue_us[job_id] = self.wait_mark_us[job_id] = usec(now)

    def _schedule_drain(self, now):
        if self.drain_scheduled or not self.queue:
            return
        self.drain_scheduled = True
        self.clock.schedule(now + 0.05, lambda t: self._drain(t))

    def _drain(self, now):
        self.drain_scheduled = False
        while self.queue:
            job = self.queue[0]
            decision = self.sched.place(job, now, explain=True)
            if not decision.placed:
                # Head-of-line: every queued job's wait since its last
                # attribution mark is charged to the reason blocking
                # the head (the counterfactual's reason), and each
                # post-window rejection of a ground-truth-bad node is
                # fidelity-scored against its failure class. Then
                # retry the whole queue on the next placement-relevant
                # event or the periodic tick.
                self._attribute_wait(now, decision)
                self._score_rejections(now, job, decision.explain)
                self.clock.schedule(now + 0.5,
                                    lambda t: self._schedule_drain(t))
                return
            self.queue.pop(0)
            self._close_wait(now, job.job_id)
            self._score_placement(now, job, decision.node)
            gen = self.attempt.get(job.job_id, 0)
            self.clock.schedule(
                now + job.duration_s,
                lambda t, j=job.job_id, g=gen: self._complete(t, j, g))

    # ---- queue-wait attribution + fidelity (ISSUE 18) ---------------------

    def _attribute_wait(self, now, decision):
        reason = decision.explain["blocking"] or decision.reason
        q_now = usec(now)
        for queued in self.queue:
            job_id = queued.job_id
            du = q_now - self.wait_mark_us.get(job_id, q_now)
            if du > 0:
                span = self.span_attr_us.setdefault(job_id, {})
                span[reason] = span.get(reason, 0) + du
            self.wait_mark_us[job_id] = q_now

    def _close_wait(self, now, job_id):
        """The job placed: the residual since the last attribution mark
        is dispatch latency (queue position + drain cadence, no
        rejection to blame), and the span's histogram folds into the
        job's closed totals. Timestamp quantization (usec) makes
        sum(job_attr_us) == job_wait_us EXACT by telescoping."""
        q_now = usec(now)
        mark = self.wait_mark_us.pop(job_id, q_now)
        span = self.span_attr_us.pop(job_id, {})
        if q_now - mark > 0:
            span["dispatch"] = span.get("dispatch", 0) + (q_now - mark)
        start = self.enqueue_us.pop(job_id, q_now)
        self.job_wait_us[job_id] = \
            self.job_wait_us.get(job_id, 0) + (q_now - start)
        attr = self.job_attr_us.setdefault(job_id, {})
        for reason in sorted(span):
            attr[reason] = attr.get(reason, 0) + span[reason]

    def _score_rejections(self, now, job, explanation):
        """Attribution fidelity: a post-convergence-window rejection of
        a node whose ground truth an injected failure holds bad must
        carry a reason from that failure's class
        (EXPLAIN_REASON_CLASSES). Rejections the failure cannot have
        caused are out of scope: insufficient-chips is allocation
        (failures never shrink published capacity), capacity-admission
        is query-wide, and class-floor only counts when the node's
        HEALTHY class would have cleared the job's floor (a silver host
        rejected for a gold job was never this failure's doing)."""
        for rejection in explanation["rejections"]:
            node = rejection["node"]
            ops = self.active_fail_ops.get(node)
            if not ops:
                continue
            if now <= self.excused_until.get(node, -1.0):
                continue  # still inside the convergence window
            reason = rejection["reason"]
            if reason in ("insufficient-chips", "capacity-admission"):
                continue
            host = self.hosts.get(node)
            if reason == "class-floor" and host is not None and \
                    clusterlib.CLASS_RANK.get(host.base_class, 0) < \
                    job.min_rank:
                continue
            expected = set()
            for op in ops:
                expected |= EXPLAIN_REASON_CLASSES.get(op, set())
            if not expected:
                continue
            if reason == "slice-member-degraded" and \
                    reason not in expected and host is not None:
                # The pinned precedence puts slice verdicts above
                # lifecycle: a preempted node whose slice a DIFFERENT
                # member's failure degraded legitimately explains as
                # slice-member-degraded. Accept when the slice is
                # ground-truth degraded (any member bad, or a member's
                # heal not yet converged so its claim is legitimately
                # stale).
                members = host.slice.members
                if any(m.gt_bad() for m in members) or \
                        any(m.name in self.up_track for m in members):
                    continue
            self.explain_checked += 1
            ok = reason in expected
            if not ok:
                self.explain_mismatched += 1
                if len(self.explain_mismatches) < 5:
                    self.explain_mismatches.append({
                        "t": round(now, 3), "job": job.job_id,
                        "node": node, "reason": reason,
                        "ops": sorted(ops)})
            for op in sorted(ops):
                bucket = self.explain_by_op.setdefault(
                    op, {"checked": 0, "mismatched": 0})
                bucket["checked"] += 1
                if not ok:
                    bucket["mismatched"] += 1

    def _score_placement(self, now, job, node):
        host = self.hosts[node]
        bad = host.gt_bad()
        excused = now <= self.excused_until.get(node, -1.0)
        self.placement_log.append((now, job.job_id, node, bad, excused))
        if bad:
            if excused:
                self.bad_within += 1
            else:
                self.bad_after += 1
                self.violations.append(
                    {"t": round(now, 3), "job": job.job_id, "node": node})
        heal_t0 = self.land_after_heal.pop(node, None)
        if heal_t0 is not None:
            self.first_land_s.append(now - heal_t0)

    def _complete(self, now, job_id, gen):
        if self.attempt.get(job_id, 0) != gen:
            return  # superseded: the job was evicted/failed and re-ran
        if self.sched.node_of(job_id) is None:
            return
        self.sched.release(job_id)
        self.jobs.pop(job_id, None)
        self.jobs_completed += 1
        self._schedule_drain(now)

    def fail_jobs_on(self, now, node):
        """Hardware turned bad under running jobs: they fail after the
        runtime's own detection delay and re-queue."""
        doomed = sorted(j for j, (n, _) in self.sched.placements.items()
                        if n == node)
        def fail(t, doomed=tuple(doomed)):
            for job_id in doomed:
                if self.sched.node_of(job_id) == node:
                    self.sched.release(job_id)
                    self.jobs_failed_bad_hw += 1
                    self._requeue(job_id, t)
            self._schedule_drain(t)
        self.clock.schedule(now + JOB_FAIL_DETECT_S, fail)

    # ---- failure bookkeeping ---------------------------------------------

    def note_down(self, now, node, op, server):
        window = CONVERGENCE_WINDOW_S[op]
        until = now + window
        if server.brownout_active(now):
            until = max(until,
                        server.brownout_until + BROWNOUT_GRACE_S)
        # A slowdown stretches publish ACKS, not the writes themselves
        # (the label flow rides the store apply): no window extension.
        self.excused_until[node] = until
        self.down_track[node] = (now, op)
        self.active_fail_ops.setdefault(node, set()).add(op)
        self.changes.mint(op, node, now)
        # A refail before the previous heal's recovery converged cancels
        # that heal's tracking: the node is down again, so neither its
        # recovery latency nor its first-landing watch can resolve — a
        # stale entry would be overwritten by the NEXT heal (losing a
        # tracked heal) or attribute a later landing to the old t0.
        self.up_track.pop(node, None)
        self.land_after_heal.pop(node, None)
        self.fail_jobs_on(now, node)

    def note_up(self, now, node, op):
        self.excused_until.pop(node, None)
        ops = self.active_fail_ops.get(node)
        if ops is not None:
            ops.discard(op)
            if not ops:
                self.active_fail_ops.pop(node, None)
        if self.down_track.pop(node, None) is not None:
            # Heal raced the label pipeline: the failure never reached
            # the scheduler, so its causal chain can never close.
            self.changes.discard(node)
        self.up_track[node] = (now, op)

    def extend_windows_for_brownout(self, now, brownout_until):
        """A brownout freezes label flow for every convergence still in
        flight — not just failures injected after it started: extend
        every open window past the brownout's end."""
        for node, until in sorted(self.excused_until.items()):
            if until > now:
                self.excused_until[node] = max(
                    until, brownout_until + BROWNOUT_GRACE_S)

    def slo_checkpoint_snap(self, now, aggregator):
        """One deterministic mid-soak snapshot, taken after the
        regression drill's chains have closed and published but before
        their folds retire: the merged fleet sketches (what the
        aggregator would label) next to the harness's exact values for
        the same folds, quantiled with the sketch's own nearest-rank
        rule so the only divergence left is bucketing error (gamma
        1.1) — the cross-check bench_gate --slo enforces. The exact
        side mirrors each host's LAST-PUBLISHED residency (what the
        merged annotation actually contained), not a recomputed time
        window — retire-vs-checkpoint boundary races would otherwise
        shift a fold across the window edge on one side only."""
        fleet = {}
        for stage in sorted(aggregator.store.stage):
            sketch = aggregator.store.stage[stage]
            if sketch.total > 0:
                fleet[stage] = {
                    "n": sketch.total,
                    "p50_ms": round(sketch.quantile(0.50), 3),
                    "p99_ms": round(sketch.quantile(0.99), 3),
                }
        by_stage = {}
        for name in sorted(self.hosts):
            for _t, stage, ms in self.hosts[name].published_slo_folds:
                by_stage.setdefault(stage, []).append(ms)
        harness = {}
        for stage in sorted(by_stage):
            values = sorted(by_stage[stage])
            def rank(q):
                return values[int(q * (len(values) - 1))]
            harness[stage] = {
                "n": len(values),
                "p50_ms": round(rank(0.50), 3),
                "p99_ms": round(rank(0.99), 3),
            }
        self.slo_checkpoint = {"t": round(now, 3), "fleet": fleet,
                               "harness": harness}


def apply_event(ev, now, server, slices, harness):
    """Dispatches one parsed ScheduleEvent into ground truth + the
    harness's scoring trackers."""
    if ev.op == "brownout":
        server.brownout(now, float(ev.args.get("secs", "5")))
        harness.extend_windows_for_brownout(now, server.brownout_until)
        return
    if ev.op == "slowdown":
        delay = float(ev.args.get("delay", "3"))
        server.slowdown(now, float(ev.args.get("secs", "10")), delay)
        return
    sl = slices[ev.slice_idx]
    if ev.op in clusterlib.HOST_OPS:
        host = sl.members[ev.host_idx]
        if ev.op == "degrade":
            host.gt_degraded = True
            harness.note_down(now, host.name, "degrade", server)
            host.probe_detect(now)
        elif ev.op == "heal":
            host.gt_degraded = False
            harness.note_up(now, host.name, "degrade")
            host.probe_detect(now)
        elif ev.op == "preempt":
            host.gt_preempting = True
            harness.note_down(now, host.name, "preempt", server)
            host.probe_detect(now)
        elif ev.op == "preempt-clear":
            host.gt_preempting = False
            harness.note_up(now, host.name, "preempt")
            host.probe_detect(now)
        elif ev.op == "wedge":
            host.gt_wedged = True
            harness.note_down(now, host.name, "wedge", server)
            sl.on_member_unreachable(now)
        elif ev.op == "unwedge":
            host.gt_wedged = False
            harness.note_up(now, host.name, "wedge")
            host.probe_detect(now)
        elif ev.op == "asym-partition":
            # Severed from the apiserver, still reachable by peers: the
            # assertion is the NON-event — no note_down, no verdict
            # degrade, no eviction. Peer relay keeps the member in the
            # merge and the leader hedges its publishes; a placement
            # onto it stays CORRECT (the hardware is fine).
            host.gt_asym = True
        elif ev.op == "asym-heal":
            host.gt_asym = False
            host.probe_detect(now)
        return
    if ev.op == "leader-kill":
        sl.leader().gt_alive = False
        sl.on_member_unreachable(now)
    elif ev.op == "leader-restart":
        for m in sl.members:
            if not m.gt_alive:
                m.gt_alive = True
                m.probe_detect(now)
    elif ev.op == "partition":
        for h in clusterlib.parse_host_range(ev.args, len(sl.members)):
            member = sl.members[h]
            member.gt_partitioned = True
            harness.note_down(now, member.name, "partition", server)
        sl.on_member_unreachable(now)
    elif ev.op == "heal-partition":
        for m in sl.members:
            if m.gt_partitioned:
                m.gt_partitioned = False
                harness.note_up(now, m.name, "partition")
                m.probe_detect(now)


# ---- one full simulation --------------------------------------------------


def run_sim(args, schedule_text):
    rng = random.Random(args.seed)
    clock = SimClock()
    server = ClusterApiServer(clock, rng, shards=args.shards)
    tracker = clusterlib.ChangeTracker()
    slices = [SimSlice(server, clock, rng, i, args.hosts, tracker)
              for i in range(args.slices)]
    hosts_by_name = {m.name: m for sl in slices for m in sl.members}
    server.tracker = tracker
    server.hosts_by_name = hosts_by_name

    sched = clusterlib.SimScheduler()
    harness = Harness(clock, rng, sched, hosts_by_name,
                      arrival_dt=1.0 / args.job_rate, tracker=tracker)
    for host in hosts_by_name.values():
        host.on_fold = lambda now, stage_ms: harness.slo_folds.extend(
            (now, stage, stage_ms[stage]) for stage in sorted(stage_ms))
    aggregator = ClusterAggregator(
        server, clock, AGG_DEBOUNCE_S, AGG_LEASE_S,
        deliver=harness.on_inventory, tracker=tracker)

    events = clusterlib.parse_schedule(schedule_text)
    storm_start, storm_end = storm_window(events)
    t_end = max(e.at for e in events) + args.drain_secs

    # The SLO regression drill: the checkpoint snapshot lands after the
    # LAST slowdown window ends (its chains closed and published) but
    # before their folds retire from the node windows.
    slowdowns = [e for e in events if e.op == "slowdown"]
    regression = None
    if slowdowns:
        last = slowdowns[-1]
        regression = {
            "start": last.at,
            "end": last.at + float(last.args.get("secs", "10")),
            "delay_s": float(last.args.get("delay", "3")),
        }

    # Rollout: hosts publish their first labels staggered across 5s
    # (hash-of-name phase, the fleet desync idiom).
    for name in sorted(hosts_by_name):
        host = hosts_by_name[name]
        clock.schedule(sinklib.hash_unit(name) * 5.0,
                       lambda now, h=host: h.mark_dirty(now))
    # Aggregator elects + LISTs once at t=8, then watches.
    aggregator.start(0.0)
    clock.schedule(8.0, lambda now: aggregator.sync(now))

    # Scheduler bootstrap at t=10: LIST (snapshot the store), then
    # watch (enrolled as a collection watcher).
    class SchedWatch:
        def on_event(self, now, node, labels):
            harness.on_label_event(now, node, labels)

    def sched_bootstrap(now):
        for node in sorted(server.objects):
            sched.on_event(node, server.objects[node])
        server.add_watcher(SchedWatch())

    clock.schedule(10.0, sched_bootstrap)

    # Jobs from t=12 to the end of the drain window.
    harness.start_arrivals(12.0, t_end - 5.0)

    for ev in events:
        clock.schedule(
            ev.at,
            lambda now, ev=ev: apply_event(ev, now, server, slices,
                                           harness))
    if regression is not None:
        clock.schedule(
            regression["end"] + 5.0,
            lambda now: harness.slo_checkpoint_snap(now, aggregator))
    clock.run(t_end)

    # ---- assemble the record ---------------------------------------------
    down_lat = [ms for op in sorted(harness.latency_ms_by_op)
                for ms in harness.latency_ms_by_op[op]]
    recovery = [s for op in sorted(harness.recovery_s_by_op)
                for s in harness.recovery_s_by_op[op]]
    storm_placements = [
        (t, bad) for (t, _, _, bad, _) in harness.placement_log
        if storm_start <= t <= storm_end]
    storm_good = sum(1 for _, bad in storm_placements if not bad)
    storm_secs = max(1e-9, storm_end - storm_start)
    unplaceable = sorted(n for n in hosts_by_name
                         if not sched.placeable(n))
    failures_by_op = {}
    for ev in events:
        failures_by_op[ev.op] = failures_by_op.get(ev.op, 0) + 1

    # Queue-wait attribution rollup: per placed job, the reason
    # histogram must sum to the measured wait EXACTLY (integer µs,
    # timestamp-quantized — see usec()).
    wait_total_us = 0
    wait_by_reason_us = {}
    wait_sum_mismatches = 0
    for job_id in sorted(harness.job_wait_us):
        attr = harness.job_attr_us.get(job_id, {})
        if sum(attr.values()) != harness.job_wait_us[job_id]:
            wait_sum_mismatches += 1
        wait_total_us += harness.job_wait_us[job_id]
        for reason in attr:
            wait_by_reason_us[reason] = \
                wait_by_reason_us.get(reason, 0) + attr[reason]
    wait_attribution = {
        "jobs": len(harness.job_wait_us),
        # Integer µs: wait_usec_total == sum(by_reason_usec.values())
        # exactly — bench_gate --explain re-adds the committed values.
        "wait_usec_total": wait_total_us,
        "by_reason_usec": {r: wait_by_reason_us[r]
                           for r in sorted(wait_by_reason_us)},
        "wait_seconds_total": round(wait_total_us / 1e6, 6),
        "sum_mismatches": wait_sum_mismatches,
    }

    record = {
        "mode": "cluster",
        "seed": args.seed,
        "slices": args.slices,
        "hosts_per_slice": args.hosts,
        "nodes": args.slices * args.hosts,
        "shards": args.shards,
        "job_rate_per_s": args.job_rate,
        "schedule_events": {op: failures_by_op[op]
                            for op in sorted(failures_by_op)},
        "jobs_submitted": harness.jobs_submitted,
        "jobs_completed": harness.jobs_completed,
        "jobs_failed_on_bad_hw": harness.jobs_failed_bad_hw,
        "jobs_requeued": harness.jobs_requeued,
        "placements_total": len(harness.placement_log),
        "decisions_total": sched.decisions,
        "no_candidate_total": sched.no_candidate_total,
        "no_capacity_total": sched.no_capacity_total,
        "scheduler_events": harness.sched_events,
        "inventory_updates_consumed": harness.inventory_updates,
        "agg_full_recomputes": aggregator.store.full_recomputes,
        "brownout_deferred_writes": server.brownout_rejected,
        "label_to_placement_p50_ms": round(percentile(down_lat, 50), 3),
        "label_to_placement_p99_ms": round(percentile(down_lat, 99), 3),
        "label_to_placement_by_op": {
            op: {"n": len(v),
                 "p99_ms": round(percentile(v, 99), 3)}
            for op, v in sorted(harness.latency_ms_by_op.items())},
        # Causal decomposition (ISSUE 15): per-failure-class stage
        # breakdown of the SAME chains the end-to-end latency measures,
        # plus the parallel agg-debounce channel and the change-id
        # propagation proof. bench_gate --cluster budget-gates each
        # stage and checks sum-consistency against the e2e numbers.
        "stage_breakdown": clusterlib.stage_breakdown(
            tracker.closed, percentile),
        "stage_breakdown_overall": clusterlib.stage_breakdown(
            [dict(c, op="all") for c in tracker.closed],
            percentile).get("all"),
        "agg_debounce_ms_by_op": {
            op: {"n": len(v),
                 "p50_ms": round(percentile(v, 50), 3),
                 "p99_ms": round(percentile(v, 99), 3)}
            for op, v in sorted(
                aggregator.agg_latency_ms_by_op.items())},
        "change_ids": {
            "minted": tracker.next_change - 1,
            "closed": len(tracker.closed),
            "discarded": tracker.discarded,
            "active_at_end": tracker.active(),
            "label_events_joined": tracker.label_events_joined,
            "inventory_joined": tracker.inventory_joined,
        },
        "failures_tracked": (len(down_lat) + len(harness.down_track)),
        "failures_converged": len(down_lat),
        "bad_placements_within_window": harness.bad_within,
        "bad_placements_after_window": harness.bad_after,
        "violations": harness.violations[:10],
        "recovery_p50_s": round(percentile(recovery, 50), 3),
        "recovery_p99_s": round(percentile(recovery, 99), 3),
        "heals_tracked": len(recovery) + len(harness.up_track),
        "heals_converged": len(recovery),
        "recovery_first_land_p99_s": round(
            percentile(harness.first_land_s, 99), 3),
        "recovery_first_land_n": len(harness.first_land_s),
        "storm_window_s": round(storm_secs, 3),
        "storm_placements": len(storm_placements),
        "storm_decisions_per_sec": round(
            len(storm_placements) / storm_secs, 3),
        "storm_good_placement_frac": round(
            storm_good / len(storm_placements), 4)
            if storm_placements else 0.0,
        "final_unplaceable_nodes": len(unplaceable),
        "final_queue_len": len(harness.queue),
        "leader_transitions": sum(sl.leader_transitions for sl in slices),
        # Partition-tolerant fast convergence (ISSUE 19): each protocol
        # upgrade must actually FIRE during the soak — bench_gate
        # --cluster requires all three non-zero on the committed record.
        "slice_relayed_reports": sum(sl.relayed_reports
                                     for sl in slices),
        "slice_successions": sum(sl.successions for sl in slices),
        "slice_hedged_publishes": sum(sl.hedged_publishes
                                      for sl in slices),
        "by_verb": {k: server.by_verb[k]
                    for k in sorted(server.by_verb)},
        # Fleet SLO engine (ISSUE 16): the burn verdict trail, the
        # regression drill's shape, and the fleet-vs-harness checkpoint
        # bench_gate --slo cross-checks within sketch error.
        "slo": {
            "window_s": SLO_WINDOW_S,
            "fast_window_s": SLO_FAST_WINDOW_S,
            "slow_window_s": SLO_SLOW_WINDOW_S,
            "budgets_ms": {s: aggregator.burn.budgets[s]
                           for s in sorted(aggregator.burn.budgets)},
            "regression": regression,
            "stretched_publishes": server.slowdown_stretched,
            "folds": {
                s: sum(1 for _, stage, _ in harness.slo_folds
                       if stage == s)
                for s in agglib.SLO_STAGES},
            "burn_edges": aggregator.burn_edges,
            "burning_at_end": aggregator.burn.burning_stages(),
            "burn_label_flushes": aggregator.burn_label_flushes,
            "checkpoint": harness.slo_checkpoint,
        },
        # Placement explainability (ISSUE 18): the rejection-taxonomy
        # rollup, the decision audit ring's counters, the exact
        # queue-wait reason attribution, and the fidelity score
        # bench_gate --explain gates.
        "explain": {
            "explained_queries": sched.explained_total,
            "rejections_total": {
                r: sched.rejections_total[r]
                for r in sorted(sched.rejections_total)},
            "ring": {
                "capacity": sched.ring_capacity,
                "appended": sched.ring_seq,
                "dropped": sched.ring_dropped,
                "evictions": sched.evicted_total,
            },
            "attribution": wait_attribution,
            "fidelity": {
                "checked": harness.explain_checked,
                "mismatched": harness.explain_mismatched,
                "by_op": {op: dict(harness.explain_by_op[op])
                          for op in sorted(harness.explain_by_op)},
                "mismatch_examples": harness.explain_mismatches,
            },
        },
    }
    return record


def check_record(record):
    """The soak's own acceptance invariants (bench_gate re-checks the
    committed record; this guards a fresh run)."""
    problems = []
    if record["bad_placements_after_window"] != 0:
        problems.append(
            f"{record['bad_placements_after_window']} job(s) placed on "
            f"known-bad hardware AFTER the convergence window "
            f"(e.g. {record['violations'][:3]}) — the labels failed "
            "placement")
    if record["failures_converged"] != record["failures_tracked"]:
        problems.append(
            f"only {record['failures_converged']} of "
            f"{record['failures_tracked']} injected failures ever "
            "reached the scheduler as a placeability flip")
    if record["heals_converged"] != record["heals_tracked"]:
        problems.append(
            f"only {record['heals_converged']} of "
            f"{record['heals_tracked']} heals made the victim "
            "placeable again")
    if record["final_unplaceable_nodes"] != 0:
        problems.append(
            f"{record['final_unplaceable_nodes']} node(s) still "
            "unplaceable after heal-all + drain")
    if record["placements_total"] == 0:
        problems.append("the job stream never placed anything")
    if record["storm_placements"] == 0:
        problems.append("no placement decisions during the storm window")
    if record["agg_full_recomputes"] != 0:
        problems.append(
            f"{record['agg_full_recomputes']} aggregator full "
            "recomputes (must stay O(delta))")
    if record["inventory_updates_consumed"] == 0:
        problems.append("the scheduler never consumed an inventory "
                        "rollup (the aggregator is not composed in)")
    asym_scheduled = record["schedule_events"].get("asym-partition", 0)
    if asym_scheduled:
        for key in ("slice_relayed_reports", "slice_hedged_publishes"):
            if not record.get(key):
                problems.append(
                    f"an asym-partition was scheduled but {key} is "
                    "zero — the ISSUE 19 relay/hedge path never fired")
    if record["schedule_events"].get("partition", 0) and \
            not record.get("slice_successions"):
        problems.append(
            "a leader-covering partition was scheduled but no "
            "pre-declared succession ever promoted a follower")
    changes = record["change_ids"]
    if changes["active_at_end"] != 0:
        problems.append(
            f"{changes['active_at_end']} change id(s) still open after "
            "heal-all + drain — a causal chain never closed or was "
            "leaked")
    if changes["closed"] != record["failures_converged"]:
        problems.append(
            f"closed chains ({changes['closed']}) != converged "
            f"failures ({record['failures_converged']}) — the stage "
            "breakdown does not cover the e2e metric")
    if changes["label_events_joined"] == 0:
        problems.append("no watch delivery ever carried a change id — "
                        "the annotation did not propagate to the "
                        "scheduler")
    # A short --quick run may legitimately see no rollup-moving event
    # coincide with an open change; but whenever the agg channel DID
    # measure a latency, the delivered inventory must have carried the
    # id (bench_gate additionally requires joins outright on the
    # committed full-schedule record).
    if record["agg_debounce_ms_by_op"] and \
            changes["inventory_joined"] == 0:
        problems.append("agg-debounce latencies recorded but no "
                        "inventory rollup carried a change id — the "
                        "aggregator echo is not composed in")
    for op, sb in sorted(record["stage_breakdown"].items()):
        if abs(sb["mean_stage_sum_ms"] - sb["mean_e2e_ms"]) > 0.01:
            problems.append(
                f"{op}: stage means sum to {sb['mean_stage_sum_ms']}ms "
                f"but the e2e mean is {sb['mean_e2e_ms']}ms — the "
                "stages do not partition the end-to-end latency")
    problems.extend(check_slo(record["slo"]))
    problems.extend(check_explain(record["explain"]))
    return problems


def check_explain(explain):
    """The explainability invariants a fresh soak run enforces on
    itself (bench_gate --explain re-checks the committed record and
    additionally requires fidelity coverage, which a --quick run may
    legitimately lack)."""
    problems = []
    if explain["explained_queries"] == 0:
        problems.append("no placement decision was ever explained — "
                        "the explain contract never ran")
    attribution = explain["attribution"]
    if attribution["sum_mismatches"] != 0:
        problems.append(
            f"{attribution['sum_mismatches']} job(s) whose queue-wait "
            "reason histogram does not sum exactly to the measured "
            "wait — an interval was dropped or double-attributed")
    if attribution["wait_usec_total"] != \
            sum(attribution["by_reason_usec"].values()):
        problems.append(
            "the aggregate reason histogram does not sum to the "
            "aggregate measured wait — attribution leaked")
    fidelity = explain["fidelity"]
    if fidelity["mismatched"] != 0:
        problems.append(
            f"{fidelity['mismatched']} post-window rejection(s) of a "
            f"ground-truth-bad node carried a reason outside its "
            f"failure's class (e.g. "
            f"{fidelity['mismatch_examples'][:3]}) — the explanations "
            "misattribute")
    unknown = [r for r in explain["rejections_total"]
               if r not in clusterlib.REJECTION_REASONS]
    if unknown:
        problems.append(f"rejection reasons outside the closed "
                        f"taxonomy: {unknown}")
    return problems


def check_slo(slo):
    """The SLO engine's own acceptance invariants (bench_gate --slo
    re-checks the committed record with the budget cross-derivation on
    top). Only enforced when the schedule ran a regression drill."""
    problems = []
    regression = slo.get("regression")
    if regression is None:
        return problems
    if not slo.get("stretched_publishes"):
        problems.append("a slowdown was scheduled but no publish was "
                        "ever stretched — the regression drill is "
                        "vacuous")
    edges = slo.get("burn_edges", [])
    window_end = regression["end"] + slo["fast_window_s"]
    # The verdict must be BURNING at some point inside the regression
    # window (an assert edge at or before window_end with no clear
    # before the window starts also covers a pre-regression assert
    # from an earlier over-budget burst).
    burning_in_window = False
    live = {}  # stage -> assert t, for burn intervals still open
    for edge in edges:
        if edge["burning"]:
            live[edge["stage"]] = edge["t"]
        else:
            asserted = live.pop(edge["stage"], None)
            if asserted is not None and asserted <= window_end and \
                    edge["t"] > regression["start"]:
                burning_in_window = True
    if any(t <= window_end for t in live.values()):
        burning_in_window = True
    if not burning_in_window:
        problems.append(
            "the regression drill never asserted a burn verdict "
            f"inside its window (through {window_end}s)")
    if slo.get("burning_at_end"):
        problems.append(
            f"stages {slo['burning_at_end']} still burning at soak "
            "end — the verdict never cleared after the heal")
    if not slo.get("burn_label_flushes"):
        problems.append("no published rollup ever carried a "
                        "tpu.slo.*.burn label — the verdict never "
                        "reached the label surface")
    checkpoint = slo.get("checkpoint")
    if not checkpoint or not checkpoint.get("fleet"):
        problems.append("the SLO checkpoint is missing or empty — the "
                        "fleet sketches never merged")
        return problems
    fleet, harness = checkpoint["fleet"], checkpoint["harness"]
    if sorted(fleet) != sorted(harness):
        problems.append(
            f"checkpoint stage sets diverge: fleet {sorted(fleet)} vs "
            f"harness {sorted(harness)}")
        return problems
    for stage in sorted(fleet):
        f, h = fleet[stage], harness[stage]
        if f["n"] != h["n"]:
            problems.append(
                f"checkpoint {stage}: fleet folded {f['n']} samples "
                f"but the harness saw {h['n']} — the annotation "
                "channel dropped or duplicated folds")
            continue
        for q in ("p50_ms", "p99_ms"):
            exact = h[q]
            got = f[q]
            # The sketch rounds UP to its bucket edge: within one
            # gamma of the exact value, floored at the sketch's
            # smallest representable value (values under SKETCH_MIN
            # all land in bucket 0, whose representative is
            # SKETCH_MIN); tiny epsilon for fixed3 rounding.
            ceiling = max(exact * 1.1, agglib.SKETCH_MIN) + 0.002
            if not (exact - 0.002 <= got <= ceiling):
                problems.append(
                    f"checkpoint {stage} {q}: fleet {got} vs harness "
                    f"{exact} — outside the gamma-1.1 sketch error")
    return problems


# ---- the sharded aggregation tree + placement soak (ISSUE 17) -------------

# Tier debounces sized so churn -> merged-root-publish stays sub-second
# even when a change lands at the very start of BOTH windows:
# L1 0.4s + wire + root 0.4s + wire < 1s.
SHARD_L1_DEBOUNCE_S = 0.4
SHARD_ROOT_DEBOUNCE_S = 0.4
# A placement answer touching a node (or its slice) whose ground truth
# changed this recently is excused, not gated: the informer feed is
# physics (wire + apply), not a correctness bug.
SHARD_CONVERGE_S = 1.0
# Every Nth query additionally pays the O(nodes) exact scan: the
# answer's WINNER must match an independent SimScheduler-style sweep
# over the ground-truth label surface.
SHARD_PARITY_EVERY = 2000
SHARD_SLICE_HOSTS = 8     # nodes per slice id in the synthetic fleet
SHARD_WIRE = (0.0005, 0.003)


def shard_node_labels(rng, i):
    """One node's published labels: every rollup dimension the tree
    must carry (classes, chips, slices, degraded claims, preemption,
    multislice, perf sketches)."""
    labels = {
        agglib.TPU_COUNT: str([4, 8, 16][i % 3]),
        agglib.PERF_CLASS:
            ["gold", "gold", "silver", "silver", "degraded", ""][i % 6],
        agglib.SLICE_ID: f"slice-{i // SHARD_SLICE_HOSTS:06d}",
        agglib.SLICE_DEGRADED: "true" if i % 97 == 0 else "false",
        agglib.PERF_MATMUL: agglib.fixed3(rng.uniform(60.0, 200.0)),
        agglib.PERF_HBM: agglib.fixed3(rng.uniform(250.0, 900.0)),
    }
    if i % 83 == 0:
        labels[agglib.LIFECYCLE_PREEMPT] = "true"
    if i % 12 == 0:
        labels[agglib.MULTISLICE_SLICE_ID] = str(i % 4)
    return labels


class ShardTreeSim:
    """The tree on one virtual clock: N L1 InventoryStore twins ->
    partial wire -> one ShardMergeStore root -> inventory delivery,
    next to a flat single-store oracle fed the identical stream, and
    the tpufd.placement index the query stream runs against. The ONLY
    wall clock in the soak wraps the placement query calls (the
    measured serving rate); everything else is virtual and seeded."""

    def __init__(self, args, rng, clock):
        self.args = args
        self.rng = rng
        self.clock = clock
        self.shards = args.shards
        self.labels = {}            # ground truth == published surface
        self.stage_slo = {}         # node -> pinned stage-slo payload
        self.flat = agglib.InventoryStore()
        self.l1 = [agglib.InventoryStore() for _ in range(self.shards)]
        self.l1_flush = [agglib.FlushController(SHARD_L1_DEBOUNCE_S)
                         for _ in range(self.shards)]
        self.l1_flush_scheduled = [False] * self.shards
        self.l1_flushes = [0] * self.shards
        self.l1_pending = [[] for _ in range(self.shards)]  # change ts
        self.root = agglib.ShardMergeStore()
        self.root_flush = agglib.FlushController(SHARD_ROOT_DEBOUNCE_S)
        self.root_flush_scheduled = False
        self.root_flushes = 0
        self.root_pending = []      # change ts merged, awaiting publish
        self.root_published = None  # last published inventory labels
        self.partial_bytes_max = 0
        self.index = placementlib.PlacementIndex()
        self.inventory_delivered = 0
        self.last_inventory = {}    # what the exact checker admits from
        # ground-truth slice claims for O(1) answer scoring
        self.gt_claims = {}
        self.gt_blocked = set()
        self.node_changed_at = {}
        self.slice_changed_at = {}
        # scoring
        self.staleness_s = []
        self.queries = {"placed": 0, "no-candidate": 0, "no-capacity": 0}
        self.incorrect_after = 0
        self.incorrect_within = 0
        self.violations = []
        self.parity_samples = 0
        self.parity_mismatches = 0
        self.query_seq = 0
        self.query_wall_s = 0.0
        self.queries_correct = 0
        self.restart_drill = None

    def _wire(self):
        return self.rng.uniform(*SHARD_WIRE)

    # ---- ground-truth slice claims (worst-of-members, O(1)) ---------------

    def _claim(self, labels):
        return (labels.get(agglib.SLICE_DEGRADED) == "true" or
                labels.get(placementlib.SLICE_CLASS) == "degraded")

    def _track_claims(self, node, old, new, now):
        for labels, delta in ((old, -1), (new, +1)):
            if labels is None or not self._claim(labels):
                continue
            sid = labels.get(agglib.SLICE_ID, "")
            if not sid:
                continue
            count = self.gt_claims.get(sid, 0) + delta
            if count <= 0:
                self.gt_claims.pop(sid, None)
                self.gt_blocked.discard(sid)
            else:
                self.gt_claims[sid] = count
                self.gt_blocked.add(sid)
            self.slice_changed_at[sid] = now

    # ---- the label stream -------------------------------------------------

    def bootstrap(self):
        """Seed the whole fleet at t=0 into every tier (bootstrap
        staleness is not tracked — the gated metric is steady-state
        churn -> merged publish)."""
        for i in range(self.args.nodes):
            node = f"tpu-node-{i:06d}"
            labels = shard_node_labels(self.rng, i)
            slo = ""
            if i % 1000 == 0:
                hot = agglib.Sketch()
                hot.add(12.0 + (i % 7) * 3.0)
                hot.add(900.0)
                slo = agglib.serialize_stage_sketches({"publish": hot})
                self.stage_slo[node] = slo
            self.labels[node] = labels
            self._track_claims(node, None, labels, 0.0)
            self.flat.apply(node, labels, stage_slo=slo)
            shard = agglib.shard_index_of(node, self.shards)
            self.l1[shard].apply(node, labels, stage_slo=slo)
            self.index.apply_node(node, labels)
            self._note_l1_dirty(shard, 0.0)
        self.slice_changed_at = {}
        self.node_changed_at = {}

    def churn(self, now):
        i = self.rng.randrange(self.args.nodes)
        node = f"tpu-node-{i:06d}"
        old = self.labels[node]
        new = dict(old)
        roll = self.rng.random()
        if roll < 0.35:
            new[agglib.PERF_CLASS] = self.rng.choice(
                ["gold", "silver", "degraded"])
        elif roll < 0.55:
            new[agglib.SLICE_DEGRADED] = \
                "false" if old.get(agglib.SLICE_DEGRADED) == "true" \
                else "true"
        elif roll < 0.70:
            if agglib.LIFECYCLE_PREEMPT in new:
                del new[agglib.LIFECYCLE_PREEMPT]
            else:
                new[agglib.LIFECYCLE_PREEMPT] = "true"
        elif roll < 0.90:
            new[agglib.PERF_MATMUL] = agglib.fixed3(
                self.rng.uniform(60.0, 200.0))
        else:
            new[agglib.TPU_COUNT] = self.rng.choice(["4", "8", "16"])
        self.labels[node] = new
        self._track_claims(node, old, new, now)
        self.node_changed_at[node] = now
        slo = self.stage_slo.get(node, "")
        self.flat.apply(node, new, stage_slo=slo)
        shard = agglib.shard_index_of(node, self.shards)
        if self.l1[shard].apply(node, new, stage_slo=slo):
            self.l1_pending[shard].append(now)
            self._note_l1_dirty(shard, now)
        # The placement informer sees the node event directly (no
        # aggregation tier on the query path) after wire latency.
        self.clock.schedule(
            now + self._wire(),
            lambda t, n=node, lb=dict(new): self.index.apply_node(n, lb))

    # ---- tier flushes (bounded-staleness debounce per tier) ---------------

    def _note_l1_dirty(self, shard, now):
        self.l1_flush[shard].note_dirty(now)
        if not self.l1_flush_scheduled[shard]:
            self.l1_flush_scheduled[shard] = True
            self.clock.schedule(self.l1_flush[shard].due_at(),
                                lambda t, s=shard: self._l1_flush(t, s))

    def _l1_flush(self, now, shard):
        self.l1_flush_scheduled[shard] = False
        if not self.l1_flush[shard].dirty:
            return
        self.l1_flush[shard].note_flushed()
        self.l1_flushes[shard] += 1
        wire = agglib.serialize_partial_labels(
            self.l1[shard].partial(), f"{shard}/{self.shards}")
        self.partial_bytes_max = max(
            self.partial_bytes_max,
            sum(len(k) + len(v) for k, v in wire.items()))
        pending, self.l1_pending[shard] = self.l1_pending[shard], []
        self.clock.schedule(
            now + self._wire(),
            lambda t, s=shard, w=wire, p=tuple(pending):
                self._root_merge(t, s, w, p))

    def _root_merge(self, now, shard, wire, pending):
        partial = agglib.parse_partial_labels(wire)
        changed = self.root.apply_partial(shard, partial)
        self.root_pending.extend(pending)
        if changed:
            self.root_flush.note_dirty(now)
            if not self.root_flush_scheduled:
                self.root_flush_scheduled = True
                self.clock.schedule(self.root_flush.due_at(),
                                    lambda t: self._root_publish(t))

    def _root_publish(self, now):
        self.root_flush_scheduled = False
        if not self.root_flush.dirty:
            return
        self.root_flush.note_flushed()
        self.root_flushes += 1
        self.root_published = self.root.build_output_labels()
        for changed_at in self.root_pending:
            self.staleness_s.append(now - changed_at)
        self.root_pending = []
        labels = dict(self.root_published)
        self.clock.schedule(
            now + self._wire(),
            lambda t, lb=labels: self._deliver_inventory(lb))

    def _deliver_inventory(self, labels):
        self.inventory_delivered += 1
        self.last_inventory = labels
        self.index.apply_inventory(labels)

    def shard_restart(self, now):
        """The retire/re-admit drill: the root drops one shard's
        partial (its lease lapsed) and the L1 republishes — the merged
        state must converge back to the oracle (the final byte-identity
        check proves the unmerge really subtracted)."""
        victim = self.shards // 2
        self.root.remove_partial(victim)
        self.root_flush.note_dirty(now)
        if not self.root_flush_scheduled:
            self.root_flush_scheduled = True
            self.clock.schedule(self.root_flush.due_at(),
                                lambda t: self._root_publish(t))
        self.restart_drill = {"shard": victim, "t": round(now, 3)}
        self.clock.schedule(now + 0.5,
                            lambda t, s=victim: self._readmit(t, s))

    def _readmit(self, now, shard):
        self.l1_flush[shard].note_dirty(now)
        self.l1_flush[shard].dirty_since = now  # force a republish
        if not self.l1_flush_scheduled[shard]:
            self.l1_flush_scheduled[shard] = True
            self.clock.schedule(self.l1_flush[shard].due_at(),
                                lambda t, s=shard: self._l1_flush(t, s))

    # ---- the query stream -------------------------------------------------

    QUERY_MIX = (("any", 1, False), ("any", 4, False), ("gold", 4, False),
                 ("silver", 8, False), ("any", 8, True), ("gold", 1, False),
                 ("any", 16, False), ("silver", 4, True))

    def query(self, now):
        self.query_seq += 1
        wanted, chips, want_slice = self.QUERY_MIX[
            self.query_seq % len(self.QUERY_MIX)]
        t0 = time.perf_counter()
        answer = self.index.query(wanted=wanted, chips=chips,
                                  slice=want_slice, limit=1)
        self.query_wall_s += time.perf_counter() - t0
        status = answer["status"]
        self.queries[status] += 1
        correct = True
        if status == "placed":
            correct = self._score_candidate(
                now, answer["candidates"][0]["node"], wanted, chips,
                want_slice)
        if self.query_seq % SHARD_PARITY_EVERY == 0:
            self._score_parity(now, answer, wanted, chips, want_slice)
        if correct:
            self.queries_correct += 1

    def _recent(self, now, node):
        if now - self.node_changed_at.get(node, -1e9) <= SHARD_CONVERGE_S:
            return True
        sid = self.labels.get(node, {}).get(agglib.SLICE_ID, "")
        return sid and now - self.slice_changed_at.get(sid, -1e9) \
            <= SHARD_CONVERGE_S

    def _score_candidate(self, now, node, wanted, chips, want_slice):
        """O(1) validity of a served candidate against ground truth:
        eligible, class floor, room, slice shape, slice not blocked."""
        labels = self.labels.get(node)
        min_rank = placementlib.job_min_rank(wanted)
        ok = (labels is not None and
              placementlib.basic_eligible(labels) and
              placementlib.class_rank(
                  labels.get(agglib.PERF_CLASS, "")) >= min_rank)
        if ok:
            raw = labels.get(agglib.TPU_COUNT, "0")
            ok = raw.isdigit() and int(raw) >= chips
        if ok:
            sid = labels.get(agglib.SLICE_ID, "")
            if want_slice and not sid:
                ok = False
            elif sid and sid in self.gt_blocked:
                ok = False
        if ok:
            return True
        if self._recent(now, node):
            self.incorrect_within += 1
        else:
            self.incorrect_after += 1
            if len(self.violations) < 10:
                self.violations.append(
                    {"t": round(now, 3), "node": node, "class": wanted,
                     "chips": chips})
        return False

    def _score_parity(self, now, answer, wanted, chips, want_slice):
        """The sampled exact check: an independent SimScheduler-style
        sweep over the ground-truth surface (cluster.py arithmetic, not
        the index's rank structures) must pick the same winner."""
        self.parity_samples += 1
        min_rank = placementlib.job_min_rank(wanted)
        admitted = True
        if self.last_inventory:
            total = 0
            for bucket, rank in (("gold", 3), ("silver", 2),
                                 ("unclassed", 0)):
                if rank >= min_rank:
                    raw = self.last_inventory.get(
                        agglib.CAPACITY_PREFIX + bucket, "0")
                    total += int(raw) if raw.isdigit() else 0
            admitted = total >= chips
        best, best_key = None, None
        if admitted:
            for node in sorted(self.labels):
                labels = self.labels[node]
                if not clusterlib.node_eligible(labels, min_rank):
                    continue
                sid = labels.get(agglib.SLICE_ID, "")
                if want_slice and not sid:
                    continue
                if sid and sid in self.gt_blocked:
                    continue
                raw = labels.get(agglib.TPU_COUNT, "0")
                free = int(raw) if raw.isdigit() else 0
                if free < chips:
                    continue
                key = (-clusterlib.class_rank(labels), -free, node)
                if best_key is None or key < best_key:
                    best, best_key = node, key
        expect = "placed" if best is not None else (
            "no-candidate" if admitted else "no-capacity")
        got = answer["status"]
        got_node = answer["candidates"][0]["node"] \
            if answer["candidates"] else None
        if got == expect and (got_node == best or got != "placed"):
            return
        # Mismatch: excused only while the involved nodes' ground truth
        # is inside the convergence window.
        involved = [n for n in (got_node, best) if n]
        if (involved and
                all(self._recent(now, n) for n in involved)) or \
                (not involved and got != expect and
                 now - max(list(self.node_changed_at.values()) or [0.0])
                 <= SHARD_CONVERGE_S):
            self.incorrect_within += 1
            return
        self.parity_mismatches += 1
        if len(self.violations) < 10:
            self.violations.append(
                {"t": round(now, 3), "parity": True, "got": got,
                 "got_node": got_node, "expect": expect, "best": best})


def run_shard_sim(args):
    rng = random.Random(args.seed)
    clock = SimClock()
    sim = ShardTreeSim(args, rng, clock)

    t0 = time.perf_counter()
    sim.bootstrap()
    bootstrap_wall_s = time.perf_counter() - t0

    churn_t0, churn_t1 = 5.0, 5.0 + args.churn_secs
    step = 1.0 / args.churn_rate
    n = int(args.churn_secs * args.churn_rate)
    for k in range(n):
        clock.schedule(churn_t0 + k * step, sim.churn)
    # The retire/re-admit drill lands mid-churn.
    clock.schedule(churn_t0 + args.churn_secs * 0.5, sim.shard_restart)
    # Queries run through the churn window and a calm tail.
    q_step = 1.0 / args.placement_qps
    q_n = int((args.churn_secs + 3.0) * args.placement_qps)
    for k in range(q_n):
        clock.schedule(churn_t0 + k * q_step, sim.query)
    # Drain: let both debounce windows flush everything out.
    t_end = churn_t1 + 5.0
    clock.run(t_end)

    merged_equals_flat = (
        sim.root.build_output_labels() == sim.flat.build_output_labels())
    published_equals_flat = (
        sim.root_published == sim.flat.build_output_labels())
    churn_window = max(1e-9, args.churn_secs)
    record = {
        "mode": "shard",
        "seed": args.seed,
        "nodes": args.nodes,
        "shards": args.shards,
        "placement_qps": args.placement_qps,
        "churn_rate_per_s": args.churn_rate,
        "churn_secs": args.churn_secs,
        "l1_debounce_s": SHARD_L1_DEBOUNCE_S,
        "root_debounce_s": SHARD_ROOT_DEBOUNCE_S,
        "converge_window_s": SHARD_CONVERGE_S,
        "churn_events": n,
        "l1_flushes": {f"shard-{i}": sim.l1_flushes[i]
                       for i in range(args.shards)},
        "l1_flush_qps_peak_shard": round(
            max(sim.l1_flushes) / churn_window, 3),
        "root_flushes": sim.root_flushes,
        "root_flush_qps": round(sim.root_flushes / churn_window, 3),
        "partial_bytes_max": sim.partial_bytes_max,
        "inventory_updates_delivered": sim.inventory_delivered,
        "staleness_n": len(sim.staleness_s),
        "inventory_staleness_p50_s": round(
            percentile(sim.staleness_s, 50), 4),
        "inventory_staleness_p99_s": round(
            percentile(sim.staleness_s, 99), 4),
        "merged_equals_flat": merged_equals_flat,
        "published_equals_flat": published_equals_flat,
        "shard_restart_drill": sim.restart_drill,
        "full_recomputes": {
            "flat": sim.flat.full_recomputes,
            "l1_max": max(s.full_recomputes for s in sim.l1),
            "root": sim.root.full_recomputes,
        },
        "placement_nodes": len(sim.index.nodes),
        "placement_eligible": sim.index.eligible(),
        "queries_total": sim.query_seq,
        "queries_by_status": {k: sim.queries[k]
                              for k in sorted(sim.queries)},
        "incorrect_after_window": sim.incorrect_after,
        "incorrect_within_window": sim.incorrect_within,
        "parity_samples": sim.parity_samples,
        "parity_mismatches": sim.parity_mismatches,
        "violations": sim.violations,
    }
    measured = {
        "bootstrap_wall_s": round(bootstrap_wall_s, 3),
        "query_wall_s": round(sim.query_wall_s, 4),
        "queries_correct": sim.queries_correct,
        "placements_per_sec_served_correctly": round(
            sim.queries_correct / max(sim.query_wall_s, 1e-9), 1),
    }
    return record, measured


def check_shard_record(record):
    """The shard soak's own acceptance invariants (bench_gate --shard
    re-checks the committed record with the 100k-scale floors on
    top)."""
    problems = []
    if not record["merged_equals_flat"]:
        problems.append("merged root state != flat single-aggregator "
                        "oracle at quiescence — the tree is not "
                        "byte-compatible")
    if not record["published_equals_flat"]:
        problems.append("the LAST PUBLISHED inventory != the flat "
                        "oracle — a trailing delta never flushed")
    if record["shard_restart_drill"] is None:
        problems.append("the shard retire/re-admit drill never ran")
    if record["staleness_n"] == 0:
        problems.append("no staleness samples — churn never crossed "
                        "the tree")
    if record["inventory_staleness_p99_s"] > 1.0:
        problems.append(
            f"inventory staleness p99 "
            f"{record['inventory_staleness_p99_s']}s exceeds the 1s "
            "sub-second-inventory bound")
    for tier, count in sorted(record["full_recomputes"].items()):
        if count != 0:
            problems.append(f"{count} full recomputes on tier {tier} "
                            "(every tier must stay O(delta))")
    if record["queries_total"] == 0:
        problems.append("the query stream never ran")
    if record["queries_by_status"]["placed"] == 0:
        problems.append("no query was ever answered 'placed'")
    if record["incorrect_after_window"] != 0:
        problems.append(
            f"{record['incorrect_after_window']} placement answer(s) "
            f"wrong AFTER the convergence window "
            f"(e.g. {record['violations'][:3]})")
    if record["parity_samples"] == 0:
        problems.append("the exact-parity sampler never fired")
    if record["parity_mismatches"] != 0:
        problems.append(
            f"{record['parity_mismatches']} sampled exact-parity "
            "mismatch(es) — the index diverged from the ground-truth "
            "sweep")
    # Bounded-staleness coalescing: a shard flushes at most once per
    # debounce window no matter the churn rate.
    bound = 1.0 / SHARD_L1_DEBOUNCE_S * 1.25 + 1.0
    if record["l1_flush_qps_peak_shard"] > bound:
        problems.append(
            f"peak per-shard flush QPS "
            f"{record['l1_flush_qps_peak_shard']} exceeds the "
            f"debounce coalescing bound {bound:.2f}")
    return problems


def main_shard(args):
    record, measured = run_shard_sim(args)
    problems = check_shard_record(record)

    if args.once:
        record["determinism_ok"] = None
    else:
        second, _ = run_shard_sim(args)
        record["determinism_ok"] = (
            canonical_bytes(record) == canonical_bytes(second))
        if not record["determinism_ok"]:
            problems.append("two runs of the same seed diverged — the "
                            "sharded tree leaked nondeterminism")
    # Wall-clock numbers ride OUTSIDE the determinism comparison and
    # the sha: they are real measurements, not simulation outputs.
    record["record_sha256"] = hashlib.sha256(
        canonical_bytes({k: v for k, v in record.items()
                         if k not in ("determinism_ok",
                                      "record_sha256")})).hexdigest()
    record["measured"] = measured

    print(json.dumps(record))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
    if problems:
        for p in problems:
            print(f"shard soak FAILED: {p}", file=sys.stderr)
        return 1
    print(
        f"shard soak OK: {record['nodes']} nodes over "
        f"{record['shards']} L1 shards, staleness p99 "
        f"{record['inventory_staleness_p99_s']}s, merged==flat "
        f"{record['merged_equals_flat']}, "
        f"{record['queries_total']} queries "
        f"({measured['placements_per_sec_served_correctly']}/s served "
        f"correctly, {record['incorrect_after_window']} wrong after "
        f"window, {record['parity_mismatches']} parity misses), "
        f"determinism "
        f"{'pinned' if record['determinism_ok'] else 'SKIPPED'}")
    return 0


# ---- the closed-loop remediation soak (ISSUE 20) --------------------------

# The remediation pipeline's protocol constants, time-compressed onto
# the virtual clock like the SLO windows above. The stage budgets are
# DERIVED from them (each stage's worst case + ~2x slack), so loosening
# a constant without re-deriving the budget fails the gate loudly.
REMEDY_OBSERVE_S = (0.05, 0.2)   # ground truth -> daemon publish lands
REMEDY_WATCH_S = (0.02, 0.1)     # store apply -> controller observation
REMEDY_DECIDE_TICK_S = 1.0       # the controller's decision cadence
REMEDY_PATCH_RTT_S = (0.02, 0.08)  # node patch issue -> apiserver ack
REMEDY_STAGE_BUDGETS_MS = {
    # evidence crosses its ground-truth threshold -> the engine SEES it:
    # one publish (<= 200ms) + one watch delivery (<= 100ms), 2x slack.
    "detect": 600.0,
    # seen -> the decision tick emits the action: one tick, ~1.6x slack.
    "decide": 1600.0,
    # emitted -> the write is issued: same tick pass.
    "act": 100.0,
    # issued -> the apiserver acks: one patch RTT (<= 80ms), ~2x slack.
    "acked": 300.0,
}
REMEDY_ENGINE_CFG = dict(
    window_s=10.0, flap_threshold=3, heal_dwell_s=4.0, cooldown_s=1.0,
    backoff_base_s=0.5, backoff_max_s=4.0, max_concurrent_cordons=3,
    domain_cap=1, rebuild_cooldown_s=20.0)
REMEDY_JOB_CHIPS = 8
REMEDY_JOB_FAIL_DETECT_S = 0.5
REMEDY_DRAIN_TICK_S = 0.25


def remedy_schedule_text():
    """The remediation drill timeline (tpufd.cluster grammar plus the
    domain declarations). Op mapping in THIS soak: `degrade` flips the
    headline class (an eligibility down-flip — crash-loop fuel);
    `degrade ... gray=1` degrades one CHIP while the headline stays
    good (the gray-failure drill); `brownout` sheds node patches
    (write-failure/backoff drill); `slowdown` models the burn verdict
    the ISSUE 16 engine derives from a stretched-write window, arming
    the slo-burn interlock; `domain-fail`/`domain-heal` flip every
    member of a declared failure domain at once (the correlated-failure
    drill the domain-cap interlock meters)."""
    return """\
domain rack-a hosts=s0/h0,s0/h1,s0/h2,s0/h3
domain rack-b hosts=s1/h0,s1/h1,s1/h2,s1/h3
domain rack-c hosts=s2/h0,s2/h1,s2/h2,s2/h3
# phase A — crash-loop flapper: 3 down-flips inside the 10s window
10   degrade s3/h0
11   heal    s3/h0
12   degrade s3/h0
13   heal    s3/h0
14   degrade s3/h0
22   heal    s3/h0
# phase B — gray chip degradation, then the rollback drill: the chip
# heals, the evidence stays retracted through the dwell, uncordon
16   degrade s3/h1 gray=1
30   heal    s3/h1
# phase C — preempt-imminent lifecycle -> drain-recommend (label only)
20   preempt s3/h2
34   preempt-clear s3/h2
# phase D — a gray failure lands INSIDE a brownout: the cordon write is
# shed, backoff arms (node-rate-limit), the retry lands after the window
38   brownout apiserver secs=3
38.5 degrade s3/h3 gray=1
50   heal    s3/h3
# phase E — the slo-burn damper: a gray failure mid-burn defers its
# cordon until the burn verdict clears
44   slowdown apiserver secs=6
45   degrade s2/h0 gray=1
56   heal    s2/h0
# phase F — the correlated domain storm: three racks flap together;
# disruption-budget + domain-cap meter the cordons, the queue backs up
# onto the one clean rack and the rebuild recommendation fires
60   domain-fail rack-a
61   domain-heal rack-a
62   domain-fail rack-a
63   domain-heal rack-a
64   domain-fail rack-a
60.5 domain-fail rack-b
61.5 domain-heal rack-b
62.5 domain-fail rack-b
63.5 domain-heal rack-b
64.5 domain-fail rack-b
61   domain-fail rack-c
62   domain-heal rack-c
63   domain-fail rack-c
64   domain-heal rack-c
65   domain-fail rack-c
78   domain-heal rack-a
79   domain-heal rack-b
80   domain-heal rack-c
"""


class RemedyStore:
    """The apiserver's two surfaces as the remediation controller sees
    them: the label CRs (read path) and the node objects (the cordon
    write path). Node patches are the ONLY mutation the controller
    performs; the dry-run proof hashes `nodes` before/after."""

    def __init__(self, names):
        self.labels = {}        # node -> published label dict
        self.nodes = {name: {"metadata": {"name": name,
                                          "resourceVersion": "1"},
                             "spec": {"unschedulable": False}}
                      for name in names}
        self.node_patches = 0
        self.write_rejects = 0
        self.brownout_until = -1.0

    def brownout(self, now, secs):
        self.brownout_until = max(self.brownout_until, now + secs)

    def patch_node(self, now, name, unschedulable):
        """Merge-patch spec.unschedulable. A browned-out server sheds
        node patches outright (server-directed pacing, Retry-After):
        the caller's backoff + re-emit is the drill."""
        if now < self.brownout_until:
            self.write_rejects += 1
            return False
        node = self.nodes[name]
        node["spec"]["unschedulable"] = bool(unschedulable)
        node["metadata"]["resourceVersion"] = str(
            int(node["metadata"]["resourceVersion"]) + 1)
        self.node_patches += 1
        return True

    def unschedulable(self, name):
        return self.nodes[name]["spec"]["unschedulable"]

    def nodes_sha(self):
        return hashlib.sha256(canonical_bytes(self.nodes)).hexdigest()


class RemedyHost:
    """Ground truth for one node in the remediation soak. Publishes the
    label surface the engine consumes; the scheduler reads the same
    published labels (never the gt_* fields)."""

    def __init__(self, clock, rng, store, name, domain):
        self.clock = clock
        self.rng = rng
        self.store = store
        self.name = name
        self.domain = domain
        self.chips = 8
        self.gt_headline = False   # headline class degraded (flap fuel)
        self.gt_gray = False       # one chip degraded, headline good
        self.gt_preempt = False
        self.on_publish = None     # callable(now, name, labels) or None

    def bad(self):
        return self.gt_headline or self.gt_gray or self.gt_preempt

    def labels(self):
        out = {
            PREFIX + "tfd.node": self.name,
            remedylib.TPU_COUNT: str(self.chips),
            remedylib.PERF_CLASS:
                "degraded" if self.gt_headline else "gold",
        }
        if self.domain:
            out[remedylib.DOMAIN_LABEL] = self.domain
        if self.gt_gray:
            out[remedylib.CHIP_CLASS_PREFIX + "0"
                + remedylib.CHIP_CLASS_SUFFIX] = "degraded"
        if self.gt_preempt:
            out[remedylib.LIFECYCLE_PREEMPT] = "true"
        return out

    def publish(self, now):
        delay = self.rng.uniform(*REMEDY_OBSERVE_S)
        self.clock.schedule(now + delay, lambda t: self._land(t))

    def _land(self, now):
        labels = self.labels()
        self.store.labels[self.name] = labels
        if self.on_publish is not None:
            self.on_publish(now, self.name, labels)


class SimRemedy:
    """The `--mode=remedy` runner twin on the virtual clock: consumes
    observations into the REAL tpufd.remedy.RemedyEngine, executes (or,
    under dry-run, journals) its actions against the RemedyStore, and
    tracks every executed action's detect->decide->act->acked chain
    with the REAL RemedyTracker. `dry_run` is a runner property — the
    engine state machine is identical in both, which is what makes the
    dry-run journal a faithful preview."""

    def __init__(self, clock, rng, store, dry_run):
        self.clock = clock
        self.rng = rng
        self.store = store
        self.dry_run = dry_run
        self.engine = remedylib.RemedyEngine(
            remedylib.RemedyConfig(**REMEDY_ENGINE_CFG))
        self.tracker = remedylib.RemedyTracker()
        self.chains = []           # closed chains (+ excused flag)
        self.intents = []          # dry-run journal (kind, node, t)
        self.detect_seen = {}      # node -> t the detect edge fired
        self.fault_since = {}      # node -> {class: gt threshold t}
        self.gt_flips = {}         # node -> injected down-flip times
        self.excused = set()       # nodes whose next chain is excused
        self.false_positives = 0
        self.reemits = 0
        self.queued_chips = lambda: 0

    # ---- ground-truth bookkeeping (fed by apply_remedy_event) -------------

    def gt_down_flip(self, node, now):
        window = self.engine.config.window_s
        flips = self.gt_flips.setdefault(node, [])
        flips.append(now)
        self.gt_flips[node] = [t for t in flips if t > now - window]
        if len(self.gt_flips[node]) >= self.engine.config.flap_threshold:
            self.fault_since.setdefault(node, {}).setdefault(
                "crash-loop",
                self.gt_flips[node][self.engine.config.flap_threshold - 1])

    def gt_set(self, node, cls, active, now):
        per = self.fault_since.setdefault(node, {})
        if active:
            per.setdefault(cls, now)
        else:
            per.pop(cls, None)
            if cls == "crash-loop":
                self.gt_flips.pop(node, None)

    # ---- the observation feed ---------------------------------------------

    def on_publish(self, now, node, labels):
        """Store apply -> this controller's watch delivery. The delay
        draws from the CONTROLLER's rng stream, so attaching a
        controller does not perturb the job/publish streams — the
        control and dry-run passes stay identical on the job side."""
        watch = self.rng.uniform(*REMEDY_WATCH_S)
        self.clock.schedule(
            now + watch,
            lambda t, ls=dict(labels): self.on_observation(t, node, ls))

    def on_observation(self, now, node, labels):
        if self.engine.observe_node(node, labels, now):
            self.detect_seen.setdefault(node, now)

    def observe_inventory(self, labels, now):
        self.engine.observe_inventory(labels, now)

    # ---- the decision loop ------------------------------------------------

    def start(self, t0):
        self.clock.schedule(t0, lambda now: self._tick(now))

    def _tick(self, now):
        self.engine.observe_demand(self.queued_chips(), now)
        actions, blocked = self.engine.tick(now)
        for node, _ in blocked:
            # An interlock deferred this node: its eventual chain
            # measures policy dwell, not pipeline latency — excused
            # from the stage budgets (still counted + gated on edges).
            self.excused.add(node)
        for action in actions:
            self._execute(action, now)
        self.clock.schedule(now + REMEDY_DECIDE_TICK_S,
                            lambda t: self._tick(t))

    def _chain_t0(self, action):
        per = self.fault_since.get(action.node, {})
        if action.kind == "cordon" and action.evidence in per:
            return per[action.evidence]
        if action.kind == "drain-recommend" and "preempt" in per:
            return per["preempt"]
        return action.detected_at

    def _execute(self, action, now):
        node = action.node
        if action.kind == "cordon":
            recent = self.fault_since.get(node, {})
            if not recent and not self.gt_flips.get(node):
                self.false_positives += 1
        excused = node in self.excused
        n = self.engine.nodes.get(node)
        if n is not None and n.fail_count > 0:
            excused = True
            self.reemits += 1
        change = self.tracker.mint(
            self._chain_op(action), node, self._chain_t0(action))
        self.tracker.stamp(change, "detect",
                           self.detect_seen.get(node, now))
        self.tracker.stamp(change, "decide", now)
        self.tracker.stamp(change, "act", now)
        if self.dry_run:
            self.intents.append(
                {"kind": action.kind, "node": node,
                 "evidence": action.evidence, "t": round(now, 3)})
            self.engine.note_action_result(node, action.kind, True, now)
            self._close(change, now, excused, node)
            return
        if action.kind in ("cordon", "uncordon"):
            rtt = self.rng.uniform(*REMEDY_PATCH_RTT_S)
            want = action.kind == "cordon"
            self.clock.schedule(
                now + rtt,
                lambda t, c=change, nd=node, w=want, k=action.kind,
                e=excused: self._ack_patch(t, c, nd, w, k, e))
        else:
            # drain/rebuild recommendations are journal + label writes,
            # never a node mutation; they ack at CR-write latency.
            rtt = self.rng.uniform(*REMEDY_PATCH_RTT_S)
            self.clock.schedule(
                now + rtt,
                lambda t, c=change, nd=node, k=action.kind,
                e=excused: self._ack_plain(t, c, nd, k, e))

    def _ack_patch(self, now, change, node, want, kind, excused):
        if self.store.patch_node(now, node, want):
            self.engine.note_action_result(node, kind, True, now)
            self._close(change, now, excused, node)
        else:
            self.engine.note_action_result(node, kind, False, now)
            self.tracker.discard(change)

    def _ack_plain(self, now, change, node, kind, excused):
        self.engine.note_action_result(node, kind, True, now)
        self._close(change, now, excused, node)

    def _close(self, change, now, excused, node):
        record = self.tracker.close(change, now)
        if record is not None:
            record["excused"] = excused
            self.chains.append(record)
        self.detect_seen.pop(node, None)
        self.excused.discard(node)

    @staticmethod
    def _chain_op(action):
        # The per-class scorecard key: the evidence class for cordons
        # ("crash-loop"/"gray"), "preempt" for drains, the action kind
        # for rollbacks and rebuilds.
        if action.kind == "cordon":
            return action.evidence
        if action.kind == "drain-recommend":
            return "preempt"
        return action.kind


def apply_remedy_event(ev, now, store, hosts, domains, remedy):
    """Dispatch one ScheduleEvent into the remedy soak's ground truth
    (op mapping documented on remedy_schedule_text)."""
    def flip_headline(host, bad):
        was_bad = not remedylib.eligible(host.labels())
        host.gt_headline = bad
        now_bad = not remedylib.eligible(host.labels())
        if remedy is not None and now_bad and not was_bad:
            remedy.gt_down_flip(host.name, now)
        host.publish(now)

    if ev.op == "brownout":
        store.brownout(now, float(ev.args.get("secs", "3")))
        return
    if ev.op == "slowdown":
        # The burn verdict the stretched-write window produces (ISSUE
        # 16), fed to the controller as the inventory CR it watches.
        secs = float(ev.args.get("secs", "6"))
        if remedy is not None:
            remedy.observe_inventory(
                {agglib.SLO_BURN_PREFIX + "publish.burn": "true"}, now)
            remedy.clock.schedule(
                now + secs,
                lambda t: remedy.observe_inventory({}, t))
        return
    if ev.op in clusterlib.DOMAIN_OPS:
        for si, hi in domains[ev.args["domain"]]:
            host = hosts[f"sim-s{si:02d}-h{hi:02d}"]
            flip_headline(host, ev.op == "domain-fail")
        return
    host = hosts[f"sim-s{ev.slice_idx:02d}-h{ev.host_idx:02d}"]
    if ev.op == "degrade":
        if ev.args.get("gray"):
            host.gt_gray = True
            if remedy is not None:
                remedy.gt_set(host.name, "gray", True, now)
            host.publish(now)
        else:
            flip_headline(host, True)
    elif ev.op == "heal":
        if host.gt_gray and remedy is not None:
            remedy.gt_set(host.name, "gray", False, now)
        host.gt_gray = False
        flip_headline(host, False)
    elif ev.op == "preempt":
        host.gt_preempt = True
        if remedy is not None:
            remedy.gt_set(host.name, "preempt", True, now)
        host.publish(now)
    elif ev.op == "preempt-clear":
        host.gt_preempt = False
        if remedy is not None:
            remedy.gt_set(host.name, "preempt", False, now)
        host.publish(now)
    else:
        raise ValueError(f"op {ev.op} has no remedy-soak mapping")


def run_remedy_pass(args, schedule_text, mode):
    """One full remediation soak pass on a fresh virtual clock. mode:
    'control' (no controller), 'dry-run' (controller journals, never
    writes), 'enforce' (controller cordons for real)."""
    # Three independent rng streams so the CONTROLLER's draws never
    # perturb the publish/job streams: control vs dry-run must stay
    # byte-identical on the job side (the dry-run faithfulness proof),
    # and control vs enforce must differ only through the cordons.
    rng_pub = random.Random(args.seed * 9176 + 11)
    rng_jobs = random.Random(args.seed * 31337 + 7)
    rng_remedy = random.Random(args.seed * 77003 + 3)
    rng = rng_jobs
    clock = SimClock()
    names = [f"sim-s{si:02d}-h{hi:02d}"
             for si in range(args.slices) for hi in range(args.hosts)]
    events, domains = clusterlib.parse_schedule_with_domains(
        schedule_text)
    store = RemedyStore(names)
    domain_of = {f"sim-s{si:02d}-h{hi:02d}": name
                 for name, members in domains.items()
                 for si, hi in members}
    hosts = {name: RemedyHost(clock, rng_pub, store, name,
                              domain_of.get(name, ""))
             for name in names}

    remedy = None
    if mode != "control":
        remedy = SimRemedy(clock, rng_remedy, store,
                           dry_run=(mode == "dry-run"))
        for host in hosts.values():
            host.on_publish = remedy.on_publish
        remedy.start(5.0)

    # ---- the job stream: labels-only scheduler + gt scoring ---------------
    queue = []                 # FIFO of (job_id, enqueue_t)
    running = {}               # job_id -> (node, gen)
    used_chips = {name: 0 for name in names}
    stats = {"submitted": 0, "completed": 0, "failed_bad_hw": 0,
             "requeued": 0, "placements": 0, "bad_placements": 0}
    submit_t = {}
    completion_s = []
    wait_ms = []
    gen = {}
    drain_live = [False]

    def queued_chips():
        return REMEDY_JOB_CHIPS * len(queue)

    if remedy is not None:
        remedy.queued_chips = queued_chips

    def complete(now, job_id, g):
        if gen.get(job_id, 0) != g or job_id not in running:
            return
        node, _ = running.pop(job_id)
        used_chips[node] -= REMEDY_JOB_CHIPS
        stats["completed"] += 1
        completion_s.append(now - submit_t[job_id])
        schedule_drain(now)

    def fail_jobs_on(now, node):
        doomed = sorted(j for j, (n, _) in running.items() if n == node)

        def fail(t, doomed=tuple(doomed)):
            for job_id in doomed:
                if job_id in running and running[job_id][0] == node:
                    running.pop(job_id)
                    used_chips[node] -= REMEDY_JOB_CHIPS
                    gen[job_id] = gen.get(job_id, 0) + 1
                    stats["failed_bad_hw"] += 1
                    stats["requeued"] += 1
                    queue.append((job_id, t))
            schedule_drain(t)

        if doomed:
            clock.schedule(now + REMEDY_JOB_FAIL_DETECT_S, fail)

    def placeable(now, name):
        labels = store.labels.get(name)
        if labels is None or not remedylib.eligible(labels):
            return False
        if store.unschedulable(name):
            return False
        return used_chips[name] + REMEDY_JOB_CHIPS <= hosts[name].chips

    def drain(now):
        drain_live[0] = False
        while queue:
            job_id, enq_t = queue[0]
            node = next((n for n in names if placeable(now, n)), None)
            if node is None:
                clock.schedule(now + REMEDY_DRAIN_TICK_S,
                               lambda t: schedule_drain(t))
                return
            queue.pop(0)
            used_chips[node] += REMEDY_JOB_CHIPS
            g = gen.get(job_id, 0)
            running[job_id] = (node, g)
            stats["placements"] += 1
            wait_ms.append((now - enq_t) * 1000.0)
            if hosts[node].bad():
                stats["bad_placements"] += 1
                fail_jobs_on(now, node)
            else:
                duration = rng.uniform(4.0, 7.0)
                clock.schedule(
                    now + duration,
                    lambda t, j=job_id, g=g: complete(t, j, g))

    def schedule_drain(now):
        if drain_live[0] or not queue:
            return
        drain_live[0] = True
        clock.schedule(now + 0.05, drain)

    def arrive(now, job_id):
        stats["submitted"] += 1
        submit_t[job_id] = now
        queue.append((job_id, now))
        schedule_drain(now)

    # Bootstrap: every host publishes its baseline, staggered.
    for name in sorted(names):
        clock.schedule(sinklib.hash_unit(name) * 2.0,
                       lambda now, h=hosts[name]: h.publish(now))
    # Jobs every 0.5s from t=5 through t=95.
    for i in range(180):
        clock.schedule(5.0 + i * 0.5,
                       lambda now, j=f"job-{i:05d}": arrive(now, j))
    for ev in events:
        clock.schedule(
            ev.at,
            lambda now, ev=ev: apply_remedy_event(
                ev, now, store, hosts, domains, remedy))
    t_end = max(e.at for e in events) + 40.0
    clock.run(t_end)

    record = {
        "mode": mode,
        "jobs_submitted": stats["submitted"],
        "jobs_completed": stats["completed"],
        "jobs_failed_on_bad_hw": stats["failed_bad_hw"],
        "jobs_requeued": stats["requeued"],
        "placements_total": stats["placements"],
        "bad_placements": stats["bad_placements"],
        "completion_p50_s": round(percentile(completion_s, 50), 3),
        "completion_p99_s": round(percentile(completion_s, 99), 3),
        "queue_wait_p99_ms": round(percentile(wait_ms, 99), 3),
        "final_queue_len": len(queue),
        "final_running": len(running),
        "node_patches": store.node_patches,
        "write_rejects": store.write_rejects,
        "nodes_sha256": store.nodes_sha(),
        "final_unschedulable": sorted(
            n for n in names if store.unschedulable(n)),
    }
    if remedy is not None:
        # Stage budgets gate the fault->acked pipeline for the three
        # evidence classes. Uncordons measure the heal DWELL by design
        # and rebuilds have no per-node fault edge, so neither is
        # budget-gated; interlock-deferred chains are excused (the
        # deferral is policy, not pipeline latency) but still counted.
        gated = [c for c in remedy.chains
                 if not c["excused"]
                 and c["op"] in ("crash-loop", "gray", "preempt")]
        violations = []
        for chain in gated:
            for stage, budget in sorted(REMEDY_STAGE_BUDGETS_MS.items()):
                if chain["stages"][stage] > budget:
                    violations.append(
                        {"change": chain["change"], "op": chain["op"],
                         "node": chain["node"], "stage": stage,
                         "ms": chain["stages"][stage], "budget_ms": budget})
        breakdown_in = [dict(c, op=c["op"]) for c in remedy.chains]
        record["remedy"] = {
            "counters": remedy.engine.counters,
            "cordoned_at_end": remedy.engine.cordoned_nodes(),
            "chains_closed": len(remedy.chains),
            "chains_budget_gated": len(gated),
            "chains_excused": len(remedy.chains) - len(gated),
            "reemits": remedy.reemits,
            "false_positives": remedy.false_positives,
            "open_chains": len(remedy.tracker.open),
            "intents": len(remedy.intents),
            "budget_violations": violations[:10],
            "budget_violations_total": len(violations),
            "stage_breakdown": clusterlib.stage_breakdown(
                breakdown_in, percentile,
                stages=remedylib.REMEDY_STAGES),
            "render_sha256": hashlib.sha256(
                remedy.engine.render_json().encode()).hexdigest(),
        }
    return record


def run_remedy_sim(args, schedule_text):
    control = run_remedy_pass(args, schedule_text, "control")
    dry = run_remedy_pass(args, schedule_text, "dry-run")
    enforce = run_remedy_pass(args, schedule_text, "enforce")
    events, domains = clusterlib.parse_schedule_with_domains(
        schedule_text)
    by_op = {}
    for ev in events:
        by_op[ev.op] = by_op.get(ev.op, 0) + 1
    enforce_remedy = enforce["remedy"]
    record = {
        "mode": "remedy",
        "seed": args.seed,
        "slices": args.slices,
        "hosts_per_slice": args.hosts,
        "nodes": args.slices * args.hosts,
        "engine_config": dict(REMEDY_ENGINE_CFG),
        "stage_budgets_ms": dict(REMEDY_STAGE_BUDGETS_MS),
        "domains": {name: [f"s{si}/h{hi}" for si, hi in members]
                    for name, members in sorted(domains.items())},
        "schedule_events": {op: by_op[op] for op in sorted(by_op)},
        "control": control,
        "dry_run": dry,
        "enforce": enforce,
        "scorecard": {
            "bad_placements": {
                "control": control["bad_placements"],
                "dry_run": dry["bad_placements"],
                "enforce": enforce["bad_placements"]},
            "completion_p99_s": {
                "control": control["completion_p99_s"],
                "dry_run": dry["completion_p99_s"],
                "enforce": enforce["completion_p99_s"]},
            "actions": enforce_remedy["counters"]["actions"],
            "blocked": enforce_remedy["counters"]["blocked"],
            "rollback_drills": enforce_remedy["counters"]["rollbacks"],
            "write_failures":
                enforce_remedy["counters"]["write_failures"],
            "false_positives": enforce_remedy["false_positives"],
            "budget_violations":
                enforce_remedy["budget_violations_total"],
            "remediated_classes": sorted(
                enforce_remedy["stage_breakdown"]),
            "dry_run_zero_writes": (
                dry["node_patches"] == 0
                and dry["nodes_sha256"] == control["nodes_sha256"]),
            "dry_run_intents": dry["remedy"]["intents"],
        },
    }
    return record


def check_remedy_record(record):
    """The remediation soak's acceptance invariants (bench_gate --remedy
    re-checks the committed record with the reference regression on
    top)."""
    problems = []
    score = record["scorecard"]
    control, dry, enforce = (record["control"], record["dry_run"],
                             record["enforce"])
    if not score["dry_run_zero_writes"]:
        problems.append(
            "dry-run mutated the node objects (patches "
            f"{dry['node_patches']}, sha match "
            f"{dry['nodes_sha256'] == control['nodes_sha256']}) — "
            "--remedy-dry-run is not byte-zero")
    if score["dry_run_intents"] == 0:
        problems.append("dry-run journaled no intents — the preview "
                        "is vacuous")
    if control["node_patches"] != 0:
        problems.append("the control pass patched a node — the "
                        "baseline is contaminated")
    if score["budget_violations"] != 0:
        problems.append(
            f"{score['budget_violations']} non-excused stage-budget "
            f"violation(s), e.g. "
            f"{enforce['remedy']['budget_violations'][:3]}")
    if score["false_positives"] != 0:
        problems.append(
            f"{score['false_positives']} cordon(s) of a node with no "
            "injected fault — the evidence pipeline misfired")
    if score["rollback_drills"] == 0:
        problems.append("no uncordon rollback ever ran — the heal "
                        "dwell drill is vacuous")
    for interlock in remedylib.INTERLOCKS:
        if score["blocked"].get(interlock, 0) == 0:
            problems.append(
                f"interlock {interlock} never fired — its drill is "
                "vacuous")
    for cls in ("crash-loop", "gray", "preempt"):
        n = enforce["remedy"]["stage_breakdown"].get(
            cls, {}).get("n", 0)
        if n == 0:
            problems.append(
                f"no closed remediation chain for evidence class "
                f"{cls} — the per-class latency scorecard has a hole")
    if score["actions"].get("rebuild-recommend", 0) == 0:
        problems.append("the capacity-gap rebuild recommendation never "
                        "fired during the domain storm")
    if score["write_failures"] == 0 or enforce["write_rejects"] == 0:
        problems.append("the brownout never rejected a cordon write — "
                        "the backoff/retry drill is vacuous")
    if enforce["remedy"]["reemits"] == 0:
        problems.append("a rejected write was never re-emitted — the "
                        "backoff retry never landed")
    if enforce["bad_placements"] >= control["bad_placements"]:
        problems.append(
            f"enforce placed {enforce['bad_placements']} jobs on bad "
            f"hardware vs control's {control['bad_placements']} — "
            "remediation did not help placement")
    # The faithfulness proof: with the controller on its own rng
    # stream, a dry-run pass must be INDISTINGUISHABLE from control on
    # the job side — same placements, same failures, same latencies.
    for key in ("bad_placements", "jobs_failed_on_bad_hw",
                "completion_p99_s", "queue_wait_p99_ms",
                "placements_total"):
        if dry[key] != control[key]:
            problems.append(
                f"dry-run {key} {dry[key]} != control {control[key]} "
                "— the dry-run controller perturbed the workload")
    # Cordons trade tail latency for correctness: removing flapping
    # capacity mid-storm may stretch the queue, but the cost is
    # budgeted — enforce p99 stays within 25% of control while the
    # doomed placements drop.
    ceiling = round(control["completion_p99_s"] * 1.25, 3)
    if enforce["completion_p99_s"] > ceiling:
        problems.append(
            f"enforce completion p99 {enforce['completion_p99_s']}s "
            f"exceeds the 1.25x-control budget {ceiling}s — the "
            "cordons cost more than the doom loops saved")
    for name, pass_record in (("dry_run", dry), ("enforce", enforce)):
        remedy = pass_record["remedy"]
        if remedy["cordoned_at_end"]:
            problems.append(
                f"{name}: nodes {remedy['cordoned_at_end']} still "
                "cordoned after heal-all + drain — a rollback leaked")
        if remedy["open_chains"] != 0:
            problems.append(
                f"{name}: {remedy['open_chains']} remediation chain(s) "
                "never closed or were leaked")
        for op, sb in sorted(remedy["stage_breakdown"].items()):
            if abs(sb["mean_stage_sum_ms"] - sb["mean_e2e_ms"]) > 0.01:
                problems.append(
                    f"{name}: {op} stage means sum to "
                    f"{sb['mean_stage_sum_ms']}ms but the e2e mean is "
                    f"{sb['mean_e2e_ms']}ms — the stages do not "
                    "partition the remediation latency")
    if enforce["final_unschedulable"]:
        problems.append(
            f"nodes {enforce['final_unschedulable']} still "
            "unschedulable at soak end")
    for name, pass_record in (("control", control), ("dry_run", dry),
                              ("enforce", enforce)):
        if pass_record["final_queue_len"] != 0:
            problems.append(f"{name}: {pass_record['final_queue_len']} "
                            "job(s) still queued at soak end")
        if pass_record["jobs_completed"] != pass_record["jobs_submitted"]:
            problems.append(
                f"{name}: only {pass_record['jobs_completed']} of "
                f"{pass_record['jobs_submitted']} jobs ever completed")
    return problems


def main_remedy(args):
    schedule_text = remedy_schedule_text()
    if args.schedule:
        with open(args.schedule) as f:
            schedule_text = f.read()
    record = run_remedy_sim(args, schedule_text)
    problems = check_remedy_record(record)

    if args.once:
        record["determinism_ok"] = None
    else:
        second = run_remedy_sim(args, schedule_text)
        record["determinism_ok"] = (
            canonical_bytes(record) == canonical_bytes(second))
        if not record["determinism_ok"]:
            problems.append("two runs of the same seed diverged — the "
                            "remediation soak leaked nondeterminism")
    record["record_sha256"] = hashlib.sha256(
        canonical_bytes({k: v for k, v in record.items()
                         if k not in ("determinism_ok",
                                      "record_sha256")})).hexdigest()

    print(json.dumps(record))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
    if problems:
        for p in problems:
            print(f"remedy soak FAILED: {p}", file=sys.stderr)
        return 1
    score = record["scorecard"]
    print(
        f"remedy soak OK: {record['nodes']} nodes / "
        f"{len(record['domains'])} domains, bad placements "
        f"control {score['bad_placements']['control']} -> enforce "
        f"{score['bad_placements']['enforce']}, completion p99 "
        f"{score['completion_p99_s']['control']}s -> "
        f"{score['completion_p99_s']['enforce']}s, "
        f"{score['rollback_drills']} rollback(s), "
        f"{score['budget_violations']} budget violations, dry-run "
        f"zero-writes {score['dry_run_zero_writes']}, determinism "
        f"{'pinned' if record['determinism_ok'] else 'SKIPPED'}")
    return 0


def canonical_bytes(record):
    return json.dumps(record, sort_keys=True,
                      separators=(",", ":")).encode()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", type=int, default=12)
    ap.add_argument("--hosts", type=int, default=4,
                    help="hosts per slice")
    ap.add_argument("--seed", type=int, default=14)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--job-rate", type=float, default=16.0,
                    help="synthetic job arrivals per virtual second")
    ap.add_argument("--drain-secs", type=float, default=25.0,
                    help="virtual seconds to run past the last heal")
    ap.add_argument("--schedule", metavar="FILE",
                    help="failure schedule (tpufd.cluster grammar) "
                         "instead of the built-in one")
    ap.add_argument("--json", help="write the soak record here")
    ap.add_argument("--quick", action="store_true",
                    help="4x3 topology, compressed schedule (CI smoke)")
    ap.add_argument("--once", action="store_true",
                    help="skip the determinism double-run")
    ap.add_argument("--remedy", action="store_true",
                    help="run the closed-loop remediation soak (ISSUE "
                         "20): control vs dry-run vs enforce passes "
                         "over the correlated-failure-domain schedule")
    ap.add_argument("--placement-qps", type=float, default=0.0,
                    help="> 0 selects the sharded-tree + placement "
                         "soak (ISSUE 17): placement queries per "
                         "virtual second against the index twin")
    ap.add_argument("--nodes", type=int, default=100000,
                    help="fleet size for the sharded-tree soak")
    ap.add_argument("--churn-rate", type=float, default=200.0,
                    help="label mutations per virtual second "
                         "(sharded-tree soak)")
    ap.add_argument("--churn-secs", type=float, default=30.0,
                    help="length of the churn window "
                         "(sharded-tree soak)")
    args = ap.parse_args(argv)

    if args.remedy:
        # Remediation mode: the 4x4 topology the built-in drill
        # schedule's domains are written against.
        args.slices = 4
        args.hosts = 4
        return main_remedy(args)

    if args.placement_qps > 0:
        # Sharded-tree mode: --shards means L1 aggregator shards, not
        # apiserver store shards.
        if args.quick:
            args.nodes = min(args.nodes, 4000)
            args.placement_qps = min(args.placement_qps, 400.0)
            args.churn_secs = min(args.churn_secs, 12.0)
        args.shards = max(2, args.shards)
        return main_shard(args)

    if args.quick:
        args.slices = min(args.slices, 4)
        args.hosts = min(args.hosts, 3)
        args.job_rate = min(args.job_rate, 4.0)
        args.drain_secs = min(args.drain_secs, 15.0)

    if args.schedule:
        with open(args.schedule) as f:
            schedule_text = f.read()
    elif args.quick:
        schedule_text = quick_schedule_text(args.slices, args.hosts)
    else:
        schedule_text = default_schedule_text(args.slices, args.hosts)

    record = run_sim(args, schedule_text)
    problems = check_record(record)

    # ---- determinism pin: the SAME seed must reproduce the record
    # byte-for-byte (virtual clock, seeded rng, sorted iteration — any
    # wall-clock or hash-order leak shows up here).
    if args.once:
        record["determinism_ok"] = None
    else:
        second = run_sim(args, schedule_text)
        record["determinism_ok"] = (
            canonical_bytes(record) == canonical_bytes(second))
        if not record["determinism_ok"]:
            a, b = canonical_bytes(record), canonical_bytes(second)
            problems.append(
                "two runs of the same seed diverged "
                f"(len {len(a)} vs {len(b)}) — the simulation leaked "
                "nondeterminism")
    record["record_sha256"] = hashlib.sha256(
        canonical_bytes({k: v for k, v in record.items()
                         if k not in ("determinism_ok",
                                      "record_sha256")})).hexdigest()

    print(json.dumps(record))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
    if problems:
        for p in problems:
            print(f"cluster soak FAILED: {p}", file=sys.stderr)
        return 1
    print(
        f"cluster soak OK: {record['nodes']} hosts in {args.slices} "
        f"slices, {record['jobs_submitted']} jobs, label->placement p99 "
        f"{record['label_to_placement_p99_ms']}ms, "
        f"{record['bad_placements_after_window']} bad placements after "
        f"window ({record['bad_placements_within_window']} excused "
        f"inside it), recovery p99 {record['recovery_p99_s']}s, storm "
        f"{record['storm_decisions_per_sec']}/s placements at "
        f"{record['storm_good_placement_frac']:.1%} good, "
        f"determinism {'pinned' if record['determinism_ok'] else 'SKIPPED'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Cluster-in-a-box fleet soak: ~1000 simulated daemon sink loops vs one
fake apiserver (ISSUE 8), plus the 10k event-driven watch-mode
simulation (`--watch`, ISSUE 12 — see watch_soak below).

What a 50k-node cluster does to one apiserver cannot be rehearsed with
one daemon process, so this harness simulates the fleet: every node is a
`tpufd.sink` twin of the daemon's sink behavior (the SAME desync math,
diff-patch flow, anti-entropy refresh, breaker + Retry-After backoff the
C++ runs — pinned by the parity tests), scheduled on a shared heap and
executed against a real `tpufd.fakes.apiserver` instance over pooled
keep-alive connections.

Phases (all seeded, all measured):

  baseline  — the reference GET+full-PUT-per-tick sink, synchronized
              cadence (no desync): churn then steady. This is the load
              profile the tentpole exists to remove.
  diff      — the new sink: fingerprint no-op fast path (no request at
              all when nothing changed), JSON-merge-patch diff writes,
              hash-of-nodename phase offset + per-tick jitter, jittered
              anti-entropy refresh: churn then steady.
  storm     — apiserver capacity capped while the whole fleet owes a
              write: proves the 429/Retry-After adaptive backoff drains
              the herd without breaker flap.
  golden    — one node driven through an identical label-change schedule
              against two fresh servers, full-update vs diff sink; the
              stored CRs must match byte-for-byte at every step.

Request accounting buckets arrivals by the tick's SCHEDULED second (the
quantity desync controls); per-request latency is measured on the wire.
Worst-bucket share >10% of a phase's writes means the fleet still herds.

Exit nonzero when an acceptance invariant fails; the regression numbers
(steady QPS, p99) are gated separately by scripts/bench_gate.py against
the committed BENCH_r08.json.

Usage:
  python3 scripts/fleet_soak.py [--nodes 1000] [--seed 8] [--json out]
      [--quick]
"""

import argparse
import collections
import heapq
import http.client
import json
import os
import random
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from tpufd.fakes.apiserver import FakeApiServer  # noqa: E402
from tpufd.fakes.simnet import (  # noqa: E402
    AggSimServer, BASE_LABELS, SimAggregator, SimApiServer, SimClock,
    SimDaemon, percentile)
from tpufd import sink as sinklib  # noqa: E402

NAMESPACE = "fleet"


class Wire:
    """Pooled keep-alive HTTP client: one connection per worker thread,
    every request timed into `latencies_ms` and counted into the
    scheduled-second bucket the caller names."""

    def __init__(self, port):
        self.port = port
        self.local = threading.local()
        self.lock = threading.Lock()
        self.latencies_ms = []
        self.buckets = collections.Counter()
        self.by_verb = collections.Counter()
        self.throttled = 0

    def _conn(self):
        conn = getattr(self.local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                              timeout=30)
            self.local.conn = conn
        return conn

    def request_fn(self, scheduled_t):
        """A tpufd.sink request callable attributing every request to
        `scheduled_t`'s second bucket."""
        def request(method, path, body, headers):
            payload = None
            if body is not None:
                payload = json.dumps(body, separators=(",", ":"))
            t0 = time.monotonic()
            for attempt in (0, 1):  # one silent retry: stale keep-alive
                conn = self._conn()
                try:
                    conn.request(method, path, payload, headers)
                    resp = conn.getresponse()
                    raw = resp.read()
                    break
                except (OSError, http.client.HTTPException):
                    self.local.conn = None
                    if attempt:
                        raise
            ms = (time.monotonic() - t0) * 1000.0
            resp_headers = dict(resp.getheaders())
            try:
                resp_body = json.loads(raw) if raw else None
            except ValueError:
                resp_body = None
            with self.lock:
                self.latencies_ms.append(ms)
                self.buckets[int(scheduled_t)] += 1
                self.by_verb[method] += 1
                if resp.status == 429:
                    self.throttled += 1
            return resp.status, resp_headers, resp_body
        return request

    def snapshot(self):
        with self.lock:
            return (list(self.latencies_ms), dict(self.buckets),
                    dict(self.by_verb), self.throttled)

    def reset(self):
        with self.lock:
            self.latencies_ms.clear()
            self.buckets.clear()
            self.by_verb.clear()
            self.throttled = 0


class Node:
    def __init__(self, index, seed, mode, interval_s, refresh_s,
                 jitter_pct):
        self.name = f"sim-node-{index:04d}"
        self.mode = mode
        self.interval_s = interval_s
        self.jitter_pct = jitter_pct if mode == "diff" else 0
        self.rng = random.Random(seed * 1000003 + index)
        # Serializes this node's ticks: on a loaded box the worker pool
        # can backlog past one interval, and two in-flight ticks for
        # the same node would race the DiffSink/Breaker state.
        self.lock = threading.Lock()
        self.labels = dict(BASE_LABELS)
        self.labels["google.com/tfd.node"] = self.name
        self.tick = 0
        self.churn_serial = 0
        self.last_write_t = None
        self.retry_pending = False
        if mode == "diff":
            self.sink = sinklib.DiffSink(self.name, NAMESPACE)
            self.refresh_s = sinklib.refresh_period_s(
                refresh_s, self.name, jitter_pct)
        else:
            self.sink = sinklib.BaselineSink(self.name, NAMESPACE)
            self.refresh_s = refresh_s
        self.breaker = sinklib.Breaker(open_after=3, cooldown_s=30.0)

    def first_due(self, start_t):
        if self.mode == "diff":
            return start_t + sinklib.phase_offset_s(
                self.interval_s, self.name, self.jitter_pct)
        return start_t  # baseline: the synchronized rollout herd

    def next_due(self, due_t):
        self.tick += 1
        return due_t + sinklib.jittered_interval_s(
            self.interval_s, self.name, self.tick, self.jitter_pct)

    def maybe_churn(self, churn_prob):
        if churn_prob > 0 and self.rng.random() < churn_prob:
            self.churn_serial += 1
            self.labels["google.com/tpu.health.probe-ms"] = str(
                self.churn_serial)

    def run_tick(self, request, now, churn_prob):
        """One simulated pass: mirrors the daemon's plan (fast no-op vs
        write) + sink flow. Returns True when a write was attempted."""
        self.maybe_churn(churn_prob)
        if self.mode == "baseline":
            # The reference sink: GET + compare (+ full PUT) every tick.
            out = self.sink.write(request, self.labels)
            if out.ok:
                self.last_write_t = now
            return True
        dirty = self.labels != self.sink.acked or not self.sink.known
        refresh_due = (self.last_write_t is not None and
                       now - self.last_write_t >= self.refresh_s)
        if not (dirty or refresh_due or self.retry_pending):
            return False  # fingerprint-clean fast pass: no request at all
        if not self.breaker.allow(now):
            self.retry_pending = True
            return False
        if refresh_due and not dirty:
            self.sink.invalidate()  # anti-entropy: reconcile for real
        out = self.sink.write(request, self.labels)
        if out.ok:
            self.breaker.record_success()
            self.last_write_t = now
            self.retry_pending = False
        elif out.retry_after_s > 0:
            # Server-directed pacing from a LIVE server: defer instead
            # of feeding the breaker's failure streak (the daemon's
            # DispatchSink makes the same call).
            self.breaker.defer(
                sinklib.spread_retry_after_s(out.retry_after_s,
                                             self.name), now)
            self.retry_pending = True
        else:
            if out.transient:
                self.breaker.record_transient_failure(now)
            self.retry_pending = True
        return True


def run_phase(wire, pool, nodes, duration_s, churn_prob, label):
    """Drives every node's tick schedule for `duration_s`, returns the
    phase record."""
    wire.reset()
    start = time.monotonic()
    end = start + duration_s
    heap = []
    for node in nodes:
        heapq.heappush(heap, (node.first_due(start), id(node), node))
    pending = []

    def execute(node, due):
        with node.lock:
            node.run_tick(wire.request_fn(due), due, churn_prob)

    while heap:
        due, _, node = heapq.heappop(heap)
        if due >= end:
            break
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(min(delay, 0.05))
            if time.monotonic() < due:
                heapq.heappush(heap, (due, id(node), node))
                continue
        pending.append(pool.submit(execute, node, due))
        nxt = node.next_due(due)
        if nxt < end:
            heapq.heappush(heap, (nxt, id(node), node))
    for f in pending:
        f.result()
    elapsed = time.monotonic() - start
    latencies, buckets, by_verb, throttled = wire.snapshot()
    total = sum(by_verb.values())
    worst = max(buckets.values()) if buckets else 0
    record = {
        "phase": label,
        "duration_s": round(elapsed, 2),
        "requests": total,
        "by_verb": by_verb,
        "qps": round(total / elapsed, 2) if elapsed else 0.0,
        "throttled_429": throttled,
        "worst_bucket": worst,
        "worst_bucket_frac": round(worst / total, 4) if total else 0.0,
        "p50_ms": round(percentile(latencies, 50), 2),
        "p99_ms": round(percentile(latencies, 99), 2),
    }
    print(json.dumps(record), flush=True)
    return record


def golden_check(seed, steps=12):
    """One node, one seeded label-change schedule, two fresh servers:
    full-update sink vs diff sink (with periodic anti-entropy
    invalidation). The stored CRs must agree at every step — the diff
    sink must never publish content the reference flow would not."""
    rng = random.Random(seed)
    schedule = []
    labels = dict(BASE_LABELS)
    for step in range(steps):
        action = rng.choice(["set", "set", "remove", "noop"])
        if action == "set":
            labels[f"google.com/tpu.g{rng.randrange(4)}"] = str(
                rng.randrange(1000))
        elif action == "remove":
            for key in list(labels):
                if key.startswith("google.com/tpu.g"):
                    del labels[key]
                    break
        schedule.append(dict(labels))

    def strip(obj):
        meta = obj.get("metadata", {})
        return {
            "labels": meta.get("labels"),
            "spec": obj.get("spec"),
        }

    with FakeApiServer() as full_server, FakeApiServer() as diff_server:
        full_wire = Wire(full_server.port)
        diff_wire = Wire(diff_server.port)
        full = sinklib.BaselineSink("golden-node", NAMESPACE)
        diff = sinklib.DiffSink("golden-node", NAMESPACE)
        key = (NAMESPACE, "tfd-features-for-golden-node")
        for step, step_labels in enumerate(schedule):
            if step % 5 == 4:
                diff.invalidate()  # the anti-entropy reconcile cadence
            out_full = full.write(full_wire.request_fn(0), step_labels)
            out_diff = diff.write(diff_wire.request_fn(0), step_labels)
            if not (out_full.ok and out_diff.ok):
                return False, f"step {step}: write failed"
            a = strip(full_server.store[key])
            b = strip(diff_server.store[key])
            if a != b:
                return False, (f"step {step}: stores diverged:\n"
                               f"full: {json.dumps(a, sort_keys=True)}\n"
                               f"diff: {json.dumps(b, sort_keys=True)}")
    return True, ""


# ---- watch-mode simulation (ISSUE 12) ------------------------------------
#
# 10k event-driven daemons cannot be rehearsed over real sockets (10k
# live watch streams = 10k parked threads), so the watch soak runs on a
# VIRTUAL clock: a seeded discrete-event simulation of the sharded
# apiserver's watch fan-out and the daemons' event-driven loops, built
# from the same tpufd.sink twins (ApplySink ladder, Breaker,
# spread_retry_after_s desync math) the parity tests pin against the
# C++. Wire-level truth — chunked watch framing, SSA semantics, 410
# resync — is pinned separately by tests/test_fleet.py against the real
# fake apiserver and by the C++ unit suites; THIS harness proves the
# fleet-scale emergent behavior: zero quiet passes, millisecond drift
# heal, a Retry-After-paced reconnect storm that drains without breaker
# flap, and bounded convergence after a partition.


#
# The SimClock / SimApiServer / SimDaemon primitives live in
# tpufd/fakes/simnet.py (ISSUE 14): ONE copy shared by this soak, the
# aggregate soak below, and scripts/cluster_soak.py.


def watch_soak(args):
    """The 10k-daemon event-driven scale proof. All virtual-time."""
    rng = random.Random(args.seed)
    clock = SimClock()
    server = SimApiServer(clock, shards=args.shards, rng=rng)
    daemons = [SimDaemon(server, clock, i, args.seed)
               for i in range(args.nodes)]
    record = {"mode": "watch", "nodes": args.nodes, "shards": args.shards,
              "seed": args.seed}
    problems = []

    # ---- join: staggered across 10 virtual seconds (a rollout, not a
    # herd — the desync phase hash spreads it in the real fleet).
    for d in daemons:
        clock.schedule(sinklib.hash_unit(d.name) * 10.0,
                       lambda now, d=d: d.join(now))
    clock.run(15.0)
    unjoined = sum(1 for d in daemons if not d.connected)
    if unjoined:
        problems.append(f"{unjoined} daemons failed to join/watch")

    # ---- quiet window: NO events for 60 virtual seconds. The headline
    # zero-poll assertion: an event-driven daemon runs ZERO passes
    # between events (the >= 10 min anti-entropy self-check is outside
    # this window by construction).
    passes_before = {d.name: d.passes for d in daemons}
    clock.run(75.0)
    quiet_passes = sum(d.passes - passes_before[d.name] for d in daemons)
    quiet_window_min = 1.0
    record["quiet_window_s"] = 60
    record["quiet_total_passes"] = quiet_passes
    record["quiet_passes_per_minute_per_daemon"] = round(
        quiet_passes / quiet_window_min / args.nodes, 6)
    if quiet_passes != 0:
        problems.append(
            f"{quiet_passes} passes ran across the fleet during a quiet "
            f"60s window (event-driven steady state must be zero)")

    # ---- external-drift heal drill: a foreign manager moves one of OUR
    # keys on 2% of the fleet (seeded times); p99 edit -> store
    # reconverged must be milliseconds, vs >= the anti-entropy refresh
    # (>= 60s) for the write-only sink.
    drilled = rng.sample(daemons, max(10, args.nodes // 50))
    for d in drilled:
        at = 80.0 + rng.uniform(0, 10.0)
        clock.schedule(at, lambda now, d=d: server.edit(
            now, d.name, "google.com/tpu.topology", "tampered"))
    clock.run(100.0)
    heals = [ms for d in drilled for ms in d.heal_latencies_ms]
    unhealed = [d.name for d in drilled
                if server.objects[d.name]["labels"].get(
                    "google.com/tpu.topology") !=
                d.labels["google.com/tpu.topology"]]
    record["drift_drills"] = len(drilled)
    record["drift_heal_p50_ms"] = round(percentile(heals, 50), 3)
    record["drift_heal_p99_ms"] = round(percentile(heals, 99), 3)
    if unhealed:
        problems.append(f"{len(unhealed)} drifted CRs never healed "
                        f"(e.g. {unhealed[:3]})")
    if not heals:
        problems.append("drift drill produced no heal samples")
    elif percentile(heals, 99) > 2000.0:
        problems.append(
            f"drift heal p99 {percentile(heals, 99):.1f}ms exceeds the "
            f"2s acceptance bound")

    # ---- reconnect storm: EVERY watch dropped at once (apiserver
    # rollover); re-establishment is capacity-capped per shard with
    # Retry-After: 1 — the fleet must drain through the pacing without
    # a single breaker open, and no 1s bucket may re-herd the server.
    server.watch_capacity = max(
        5, args.nodes // args.shards // 20)  # ~20s nominal drain/shard
    server.watch_buckets.clear()
    storm_at = 110.0
    clock.schedule(storm_at, lambda now: [
        d.drop(now) for d in server.drop_all_watches(now)])
    clock.run(storm_at + 120.0)
    server.watch_capacity = 0
    reconnect_attempts = collections.Counter()
    for (shard, sec), n in server.watch_buckets.items():
        reconnect_attempts[sec] += n
    # The first wave (the 1-2s after the drop) sees most of the fleet by
    # construction — everyone was disconnected at the same instant and
    # retries backoff_initial later; a watch attempt is one cheap
    # request. The herd metric is whether the Retry-After-paced RETRY
    # waves after it re-converge instead of spreading.
    first_second = sum(n for sec, n in reconnect_attempts.items()
                       if sec <= int(storm_at) + 2)
    retry_buckets = {sec: n for sec, n in reconnect_attempts.items()
                     if sec > int(storm_at) + 2}
    worst_reconnect = max(retry_buckets.values()) if retry_buckets else 0
    unreconnected = sum(1 for d in daemons if not d.connected)
    reconnect_times = [d.reconnected_at - storm_at for d in daemons
                       if d.reconnected_at and d.reconnected_at >= storm_at]
    record["storm_watchers_dropped"] = args.nodes
    record["storm_drop_second_attempts"] = first_second
    record["storm_worst_1s_bucket"] = worst_reconnect
    record["storm_worst_1s_bucket_frac"] = round(
        worst_reconnect / args.nodes, 4)
    record["storm_breaker_opens"] = sum(d.breaker.opens() for d in daemons)
    record["storm_drain_p99_s"] = round(percentile(reconnect_times, 99), 2)
    record["storm_undrained"] = unreconnected
    if unreconnected:
        problems.append(f"{unreconnected} daemons never re-established "
                        f"their watch after the storm")
    if record["storm_breaker_opens"]:
        problems.append(
            f"the reconnect storm opened "
            f"{record['storm_breaker_opens']} breaker(s): Retry-After "
            f"pacing must read as a live server")
    if worst_reconnect / args.nodes > 0.25:
        problems.append(
            f"worst reconnect second saw {worst_reconnect} attempts = "
            f"{worst_reconnect / args.nodes:.0%} of the fleet (pacing "
            f"failed to spread the herd)")

    # ---- partition + convergence: 10% of the fleet loses connectivity
    # for 20s while chaos edits their CRs; convergence-after-partition
    # p99 = heal completion after the partition lifts.
    part_at = clock.now + 5.0
    victims = rng.sample(daemons, args.nodes // 10)

    def start_partition(now):
        for d in victims:
            server.partitioned.add(d.name)
        for d in victims:
            server.edit(now + 0.5, d.name, "google.com/tpu.topology",
                        "partition-tamper")

    def end_partition(now):
        for d in victims:
            server.partitioned.discard(d.name)
            # The dropped watch surfaced as a transport error when the
            # stream died; model the reconnect probe cadence finding the
            # healed network within its (jittered) backoff window.
            d.connected = False
            d.clock.schedule(
                now + sinklib.spread_retry_after_s(1.0, d.name),
                lambda t, d=d: d.connect(t))

    clock.schedule(part_at, start_partition)
    clock.schedule(part_at + 20.0, end_partition)
    clock.run(part_at + 90.0)
    # Convergence = time from the partition lifting until the victim's
    # watch re-established AND its re-list drift check re-asserted (the
    # reconnect path heals synchronously in connect(), so the
    # re-establish time IS the converged time).
    converge = []
    for d in victims:
        if d.reconnected_at and d.reconnected_at > part_at:
            converge.append(d.reconnected_at - (part_at + 20.0))
    part_unhealed = [
        d.name for d in victims
        if server.objects[d.name]["labels"].get(
            "google.com/tpu.topology") !=
        d.labels["google.com/tpu.topology"]]
    record["partition_victims"] = len(victims)
    record["partition_converge_p50_s"] = round(percentile(converge, 50), 3)
    record["partition_converge_p99_s"] = round(percentile(converge, 99), 3)
    if part_unhealed:
        problems.append(
            f"{len(part_unhealed)} partitioned CRs never reconverged "
            f"after the partition lifted (e.g. {part_unhealed[:3]})")
    if not converge:
        problems.append("partition drill produced no convergence samples")
    elif percentile(converge, 99) > 30.0:
        problems.append(
            f"convergence-after-partition p99 "
            f"{percentile(converge, 99):.1f}s exceeds the 30s bound")

    record["total_requests"] = sum(server.by_verb.values())
    record["by_verb"] = dict(server.by_verb)

    print(json.dumps(record))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
    if problems:
        for p in problems:
            print(f"watch soak FAILED: {p}", file=sys.stderr)
        return 1
    print(
        f"watch soak OK: {args.nodes} daemons x {args.shards} shards, "
        f"quiet window {record['quiet_total_passes']} passes, drift heal "
        f"p99 {record['drift_heal_p99_ms']}ms, storm drained p99 "
        f"{record['storm_drain_p99_s']}s with 0 breaker opens (worst 1s "
        f"bucket {record['storm_worst_1s_bucket_frac']:.1%}), partition "
        f"converge p99 {record['partition_converge_p99_s']}s")
    return 0


# ---- aggregate-mode simulation (ISSUE 13) --------------------------------
#
# The cluster-inventory aggregator at 10k nodes: one lease-elected
# singleton consuming every NodeFeature delta through a collection
# watch, maintaining rollups INCREMENTALLY (tpufd.agg — the parity-
# pinned twin of src/tfd/agg), and publishing through the coalescing
# debounce. Wire-level truth (collection LIST/WATCH framing, 410,
# labelSelector) is pinned by tests/test_agg.py against the real fake
# apiserver and by the real-process smoke in tests/test_fleet.py; THIS
# harness proves the fleet-scale emergent behavior on the virtual
# clock: single-node-change -> rollup-published p99 within the
# debounce + 1s bound, steady aggregator apiserver QPS <= 1 regardless
# of fleet size, ZERO full recomputes after the initial sync, and a
# 1000-node churn burst coalescing to <= 3 output writes.


#
# AggSimServer / SimAggregator live in tpufd/fakes/simnet.py too
# (ISSUE 14): the cluster soak composes the same aggregator model.


def aggregate_soak(args):
    """The 10k-node aggregator scale proof. All virtual-time."""
    from tpufd import agg as agglib

    rng = random.Random(args.seed)
    clock = SimClock()
    server = AggSimServer(clock, rng)
    debounce_s = args.agg_debounce
    lease_s = 30.0
    aggregator = SimAggregator(server, clock, debounce_s, lease_s)
    record = {"mode": "aggregate", "nodes": args.nodes,
              "seed": args.seed, "debounce_s": debounce_s,
              "lease_s": lease_s}
    problems = []

    def labels_for(i, perf_class=None, degraded=None):
        cls = perf_class or ("degraded" if i % 19 == 0 else
                             "silver" if i % 3 == 0 else "gold")
        deg = degraded if degraded is not None else (
            "true" if i % 37 == 0 else "false")
        return {
            "google.com/tpu.count": "4",
            "google.com/tpu.slice.id": f"slice-{i // 16:04d}",
            "google.com/tpu.slice.degraded": deg,
            "google.com/tpu.perf.class": cls,
            "google.com/tpu.perf.matmul-tflops":
                "%.3f" % (120.0 + (i * 13) % 80),
            "google.com/tpu.perf.hbm-gbps":
                "%.3f" % (500.0 + (i * 7) % 300),
        }

    # ---- rollout: the fleet lands over 10 virtual seconds; the
    # aggregator elects, lists ONCE at t=15, then watches.
    for i in range(args.nodes):
        at = sinklib.hash_unit(f"agg-node-{i}") * 10.0
        clock.schedule(at, lambda now, i=i: server.daemon_apply(
            now, f"node-{i:05d}", labels_for(i)))
    aggregator.start(0.0)
    clock.schedule(15.0, lambda now: aggregator.sync(now))
    clock.run(20.0)
    record["sync_nodes"] = len(aggregator.store.nodes)
    if record["sync_nodes"] != args.nodes:
        problems.append(
            f"initial sync retained {record['sync_nodes']} of "
            f"{args.nodes} nodes")

    # ---- single-node-change drills: seeded class flips spread across
    # a steady hour-shaped window; latency = change -> the first output
    # write carrying it (the acceptance bound: debounce + 1s).
    drills = max(50, args.nodes // 50)
    steady_start, steady_end = 30.0, 30.0 + args.agg_steady_secs
    for d in range(drills):
        i = rng.randrange(args.nodes)
        at = rng.uniform(steady_start, steady_end - debounce_s - 2)
        clock.schedule(at, lambda now, i=i: server.daemon_apply(
            now, f"node-{i:05d}",
            labels_for(i, perf_class="degraded", degraded="true")))
    clock.run(steady_end)
    steady_lat = list(aggregator.publish_latencies_ms)
    record["publish_drills"] = drills
    record["publish_p50_ms"] = round(percentile(steady_lat, 50), 2)
    record["publish_p99_ms"] = round(percentile(steady_lat, 99), 2)
    bound_ms = debounce_s * 1000.0 + 1000.0
    if not steady_lat:
        problems.append("no publish-latency samples")
    elif percentile(steady_lat, 99) > bound_ms:
        problems.append(
            f"single-node-change -> rollup-published p99 "
            f"{percentile(steady_lat, 99):.0f}ms exceeds the "
            f"debounce+1s bound ({bound_ms:.0f}ms)")

    # ---- steady aggregator QPS: lease renewals + coalesced flushes,
    # measured across the drill window. The contract: <= 1 QPS
    # REGARDLESS of fleet size (nothing above scales with nodes).
    window = [n for sec, n in server.agg_requests.items()
              if steady_start <= sec < steady_end]
    steady_qps = sum(window) / max(1.0, steady_end - steady_start)
    record["steady_qps"] = round(steady_qps, 3)
    record["steady_worst_second"] = max(window) if window else 0
    if steady_qps > 1.0:
        problems.append(
            f"aggregator steady apiserver QPS {steady_qps:.2f} exceeds "
            f"1.0 (must be fleet-size-independent)")

    # ---- 1000-node churn burst: every flip lands inside one debounce
    # window; the output must coalesce to <= 3 writes.
    burst_at = clock.now + 5.0
    burst_n = min(1000, args.nodes)
    victims = rng.sample(range(args.nodes), burst_n)
    for i in victims:
        at = burst_at + rng.uniform(0.0, min(0.5, debounce_s / 2))
        clock.schedule(at, lambda now, i=i: server.daemon_apply(
            now, f"node-{i:05d}", labels_for(i, perf_class="silver",
                                             degraded="false")))
    writes_before = len(server.output_writes)
    clock.run(burst_at + debounce_s * 3 + 2.0)
    burst_writes = len(server.output_writes) - writes_before
    record["burst_flips"] = burst_n
    record["burst_writes"] = burst_writes
    if burst_writes > 3:
        problems.append(
            f"a {burst_n}-node churn burst produced {burst_writes} "
            f"output writes (coalescing bound: 3)")

    # ---- the incremental-update contract: zero full recomputes after
    # sync, and the incremental state equals a from-scratch rebuild.
    record["full_recomputes"] = aggregator.store.full_recomputes
    if aggregator.store.full_recomputes != 0:
        problems.append(
            f"{aggregator.store.full_recomputes} full recomputes ran "
            f"(the steady path must be O(delta), never O(fleet))")
    fresh = agglib.InventoryStore()
    for node, labels in server.objects.items():
        fresh.apply(node, labels)
    record["incremental_equals_full"] = (
        aggregator.store.build_output_labels() ==
        fresh.build_output_labels())
    if not record["incremental_equals_full"]:
        problems.append("incremental rollups diverged from a "
                        "from-scratch rebuild")
    record["events_consumed"] = aggregator.store.events
    record["output_writes_total"] = len(server.output_writes)
    record["by_verb"] = dict(server.by_verb)

    print(json.dumps(record))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
    if problems:
        for p in problems:
            print(f"aggregate soak FAILED: {p}", file=sys.stderr)
        return 1
    print(
        f"aggregate soak OK: {args.nodes} nodes, publish p99 "
        f"{record['publish_p99_ms']}ms <= {bound_ms:.0f}ms, steady "
        f"{record['steady_qps']} qps <= 1, {burst_n}-flip burst -> "
        f"{burst_writes} writes, 0 full recomputes "
        f"({record['events_consumed']} incremental events)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=8)
    ap.add_argument("--interval", type=float, default=5.0,
                    help="simulated rewrite cadence (s)")
    ap.add_argument("--refresh", type=float, default=30.0,
                    help="anti-entropy base period (s)")
    ap.add_argument("--jitter-pct", type=int, default=10)
    ap.add_argument("--churn-secs", type=float, default=12.0)
    ap.add_argument("--steady-secs", type=float, default=18.0)
    ap.add_argument("--storm-secs", type=float, default=10.0)
    ap.add_argument("--storm-capacity", type=int, default=0,
                    help="apiserver requests/s during the storm "
                         "(0 = fleet/10)")
    ap.add_argument("--workers", type=int, default=64)
    ap.add_argument("--json", help="write the soak record here")
    ap.add_argument("--quick", action="store_true",
                    help="40 nodes, short phases (test smoke)")
    ap.add_argument("--watch", action="store_true",
                    help="run the event-driven watch-mode simulation "
                         "(virtual clock, 10k daemons) instead of the "
                         "wire-level diff-sink soak")
    ap.add_argument("--shards", type=int, default=8,
                    help="watch mode: fake apiserver shard count")
    ap.add_argument("--aggregate", action="store_true",
                    help="run the cluster-inventory aggregator "
                         "simulation (virtual clock, 10k daemons + the "
                         "sim aggregator) instead of the diff-sink soak")
    ap.add_argument("--agg-debounce", type=float, default=2.0,
                    help="aggregate mode: publish debounce (s)")
    ap.add_argument("--agg-steady-secs", type=float, default=60.0,
                    help="aggregate mode: drill/steady window (s)")
    args = ap.parse_args(argv)

    if args.aggregate:
        if args.nodes == 1000:  # the diff-soak default; aggregate is 10k
            args.nodes = 10000
        if args.quick:
            args.nodes = min(args.nodes, 400)
        return aggregate_soak(args)

    if args.watch:
        if args.nodes == 1000:  # the diff-soak default; watch mode is 10k
            args.nodes = 10000
        if args.quick:
            args.nodes = min(args.nodes, 400)
        return watch_soak(args)

    if args.quick:
        args.nodes = min(args.nodes, 40)
        args.churn_secs = min(args.churn_secs, 6.0)
        args.steady_secs = min(args.steady_secs, 6.0)
        args.storm_secs = min(args.storm_secs, 6.0)

    record = {"nodes": args.nodes, "seed": args.seed,
              "interval_s": args.interval, "refresh_s": args.refresh,
              "jitter_pct": args.jitter_pct, "phases": {}}
    problems = []

    with FakeApiServer() as server:
        wire = Wire(server.port)
        pool = ThreadPoolExecutor(max_workers=args.workers)

        def fleet(mode):
            return [Node(i, args.seed, mode, args.interval, args.refresh,
                         args.jitter_pct) for i in range(args.nodes)]

        # Both modes get an unmeasured warm-up pass first (every node
        # creates its CR): pods create once per lifetime, so the create
        # burst is a rollout event, not part of the steady/churn load
        # profile the phases below measure.
        create_secs = args.interval + 1

        # ---- baseline: the reference GET+PUT sink, synchronized.
        nodes = fleet("baseline")
        record["phases"]["baseline_create"] = run_phase(
            wire, pool, nodes, create_secs, 0.0, "baseline_create")
        record["phases"]["baseline_churn"] = run_phase(
            wire, pool, nodes, args.churn_secs, 0.3, "baseline_churn")
        record["phases"]["baseline_steady"] = run_phase(
            wire, pool, nodes, args.steady_secs, 0.0, "baseline_steady")

        # ---- diff sink + desync. Fresh store so create costs are
        # comparable; same seed so churn draws are identical.
        server.store.clear()
        nodes = fleet("diff")
        record["phases"]["diff_create"] = run_phase(
            wire, pool, nodes, create_secs, 0.0, "diff_create")
        record["phases"]["diff_churn"] = run_phase(
            wire, pool, nodes, args.churn_secs, 0.3, "diff_churn")
        record["phases"]["diff_steady"] = run_phase(
            wire, pool, nodes, args.steady_secs, 0.0, "diff_steady")

        # ---- 429 storm: cap the apiserver while the whole fleet owes
        # a write (one synchronized churn burst), then measure drain.
        capacity = args.storm_capacity or max(10, args.nodes // 10)
        for node in nodes:
            node.maybe_churn(1.0)  # everyone dirty at once
        server.set_capacity(capacity)
        storm = run_phase(wire, pool, nodes, args.storm_secs, 0.0, "storm")
        server.set_capacity(0)
        # Drain window: every deferred/pending node retries at its next
        # (jittered) tick, so 1.5 intervals + margin covers the worst
        # phase slot.
        drain = run_phase(wire, pool, nodes,
                          max(8.0, 1.5 * args.interval + 2), 0.0,
                          "storm_drain")
        record["phases"]["storm"] = storm
        record["phases"]["storm_drain"] = drain
        storm["breaker_opens"] = sum(n.breaker.opens() for n in nodes)
        storm["undrained_nodes"] = sum(
            1 for n in nodes if n.retry_pending)
        pool.shutdown()

    # ---- golden: diff-sink content == full-update content, always.
    golden_ok, golden_detail = golden_check(args.seed)
    record["golden_equal"] = golden_ok

    # ---- headline numbers + acceptance invariants.
    base_steady = record["phases"]["baseline_steady"]
    diff_steady = record["phases"]["diff_steady"]
    reduction = (base_steady["qps"] / diff_steady["qps"]
                 if diff_steady["qps"] else float("inf"))
    record["steady_qps_baseline"] = base_steady["qps"]
    record["steady_qps_diff"] = diff_steady["qps"]
    record["steady_qps_reduction"] = round(min(reduction, 9999.0), 2)
    record["steady_p99_ms"] = diff_steady["p99_ms"]
    record["churn_p99_ms"] = record["phases"]["diff_churn"]["p99_ms"]
    record["churn_p99_baseline_ms"] = (
        record["phases"]["baseline_churn"]["p99_ms"])
    record["steady_worst_bucket_frac"] = diff_steady["worst_bucket_frac"]

    if reduction < 5.0:
        problems.append(
            f"steady-state QPS only dropped {reduction:.1f}x vs the "
            f"GET+PUT baseline (need >= 5x)")
    # Thundering-herd bound: no 1-second bucket may see more than 10%
    # of the FLEET's writes — the herd metric is how much of the
    # cluster arrives together, so it scales with node count, not with
    # how long a phase happened to run. (The synchronized baseline
    # delivers the entire fleet into one bucket: frac 1.0.)
    for phase in ("diff_churn", "diff_steady"):
        worst = record["phases"][phase]["worst_bucket"]
        fleet_frac = worst / args.nodes
        record["phases"][phase]["worst_bucket_fleet_frac"] = round(
            fleet_frac, 4)
        # Gated only with a statistically meaningful sample — the
        # --quick smoke's handful of writes can land anywhere.
        if record["phases"][phase]["requests"] >= 50 and fleet_frac > 0.10:
            problems.append(
                f"{phase}: worst 1-second bucket got {worst} requests = "
                f"{fleet_frac:.0%} of the fleet (desync failed, herd "
                f"survives)")
    record["steady_worst_bucket_fleet_frac"] = round(
        record["phases"]["diff_steady"]["worst_bucket"] / args.nodes, 4)
    record["baseline_worst_bucket_fleet_frac"] = round(
        record["phases"]["baseline_steady"]["worst_bucket"] / args.nodes,
        4)
    if storm["throttled_429"] == 0:
        problems.append("storm phase saw no 429s (storm did not happen)")
    if storm["breaker_opens"] > 0:
        problems.append(
            f"storm opened {storm['breaker_opens']} breaker(s): the "
            f"Retry-After backoff should drain the herd without flap")
    if storm["undrained_nodes"] > 0:
        problems.append(
            f"{storm['undrained_nodes']} node(s) still owe a write "
            f"after the drain window")
    if not golden_ok:
        problems.append(f"golden divergence: {golden_detail}")

    print(json.dumps(record))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
    if problems:
        for p in problems:
            print(f"fleet soak FAILED: {p}", file=sys.stderr)
        return 1
    print(
        f"fleet soak OK: {args.nodes} nodes, steady "
        f"{base_steady['qps']} -> {diff_steady['qps']} qps "
        f"({reduction:.1f}x), worst steady bucket "
        f"{record['steady_worst_bucket_fleet_frac']:.1%} of the fleet "
        f"(baseline {record['baseline_worst_bucket_fleet_frac']:.0%}), "
        f"p99 {diff_steady['p99_ms']}ms, storm drained without breaker "
        f"flap, golden equal")
    return 0


if __name__ == "__main__":
    sys.exit(main())

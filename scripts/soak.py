#!/usr/bin/env python3
"""Soak-test the daemon: run it in daemon mode for a while and prove the
steady state is actually steady — memory flat, fds flat, labels stable,
rewrites on cadence, clean shutdown.

The unit/CLI tiers prove each pass is CORRECT; CI's sanitizer job proves
a pass doesn't corrupt memory. Neither catches the classic daemon
failure modes: a slow per-pass heap or fd leak, label churn between
passes, or a rewrite cadence that drifts. This harness runs the shipped
binary long enough for those to show (reference analogue: GFD's e2e tier
watches the daemon relabel on cadence, tests/e2e-tests.py — but nothing
in the reference watches its memory; this goes further).

Pass counting comes from the daemon's OWN introspection server: the
harness starts it on a loopback port and scrapes `tfd_rewrites_total`
from /metrics — the counter increments exactly once per attempted pass,
so the soak measures what the daemon says it did, not what the harness
managed to infer from mtimes or request streams. (Binaries without the
introspection server — the hermetic harness-failure fakes — fall back to
sink-observed generations; `gen_source` records which path counted.)
/readyz must also report ready at the end of a healthy soak.

Both output sinks soak: `--sink=file` (default) watches the NFD feature
file; `--sink=cr` launches the hermetic fake apiserver
(tpufd.fakes.apiserver). The CR request stream is demoted to a
cross-check: the server-side count of per-pass GETs (steady-state passes
are deliberate no-op GETs — identical labels skip the PUT, so
resourceVersion never advances) must agree with the scraped counter.

Usage:
  python3 scripts/soak.py --binary build/tpu-feature-discovery \
      --duration 30 [--interval 1] [--sink=file|cr] \
      [--extra-arg=--backend=mock ...]

Prints ONE JSON line, e.g.:
  {"ok": true, "passes": 29, "rss_start_kb": 3180, "rss_end_kb": 3180,
   "rss_drift_kb": 0, "fd_start": 6, "fd_end": 6, "labels_stable": true,
   "rewrite_interval_p50_s": 1.0, "cadence_ok": true, "readyz_ok": true,
   "gen_source": "metrics", "clean_exit": true}

Exit code 0 iff ok. "ok" means: >=3 passes observed, rewrites on cadence
(passes >= half of duration/interval AND the p50 rewrite interval within
3x --interval), RSS drift under --max-rss-drift-kb (default 1024), fd
count not above the baseline, labels (minus the timestamp) identical across every
pass, /readyz ready at soak end (when scraping), the CR GET cross-check
consistent (cr sink + scraping), SIGTERM led to exit 0, and the sink was
left in its contracted end state (file removed; the CR persists by
design — NFD owns its lifecycle).

--require-journal additionally enforces the flight-recorder
explainability invariant (tpufd.journal): every observed label change
has a matching /debug/journal label-diff event with provenance, every
observed degradation level was journaled as a transition, /debug/labels
agrees with the label file byte-for-byte, and the journal stays within
its capacity. Under that flag label CHURN is allowed as long as every
change is explained (an injected wedge SHOULD change labels);
labels_stable becomes informational.
"""

import argparse
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from tpufd import journal as tpufd_journal  # noqa: E402
from tpufd import metrics as tpufd_metrics  # noqa: E402
from tpufd.fakes import free_loopback_port  # noqa: E402


class MetricsScraper:
    """Scrapes the daemon's introspection server (the /metrics and
    /readyz the deployment probes hit), parsing with the shared
    tpufd.metrics exposition parser."""

    def __init__(self, port):
        self.base = f"http://127.0.0.1:{port}"

    def _get(self, path):
        try:
            with urllib.request.urlopen(self.base + path, timeout=2) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:  # 503 from /readyz
            return e.code, ""
        except (OSError, ValueError):
            return None, ""

    def generation(self):
        """Value of tfd_rewrites_total, or None while unreachable."""
        status, text = self._get("/metrics")
        if status != 200:
            return None
        try:
            return tpufd_metrics.sample_value(text, "tfd_rewrites_total")
        except ValueError:
            return None

    def readyz(self):
        return self._get("/readyz")[0]

    def get_json(self, path):
        """Parsed JSON document from a /debug endpoint, or None."""
        import json

        status, text = self._get(path)
        if status != 200:
            return None
        try:
            return json.loads(text)
        except ValueError:
            return None

    def counter(self, name):
        """Value of a counter, or None. `name` may carry one label
        selector: tfd_probe_attempts_total{source=health}."""
        status, text = self._get("/metrics")
        if status != 200:
            return None
        labels = None
        if "{" in name:
            name, _, selector = name.partition("{")
            key, _, value = selector.rstrip("}").partition("=")
            labels = {key: value.strip('"')}
        try:
            return tpufd_metrics.sample_value(text, name, labels=labels)
        except ValueError:
            return None

    def by_source(self, name):
        """{source: value} for every child of a source-labelled family."""
        status, text = self._get("/metrics")
        if status != 200:
            return {}
        out = {}
        try:
            for sample, labels, value in tpufd_metrics.parse_samples(text):
                if sample == name and "source" in labels:
                    out[labels["source"]] = value
        except ValueError:
            return {}
        return out


def rss_kb(pid):
    """Resident set size in KiB from /proc (Linux; the daemon's target)."""
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    raise RuntimeError("no VmRSS in /proc status")


def fd_count(pid):
    """Minimum of a few spaced samples: the probe workers legitimately
    open short-lived fds (fixture reads, metadata sockets, watchdog
    pipes) on their own threads, so a single sample can catch one
    mid-probe and read as a leak. A real leak is monotone and survives
    the min; transient probe fds do not."""
    counts = []
    for _ in range(3):
        counts.append(len(os.listdir(f"/proc/{pid}/fd")))
        time.sleep(0.05)
    return min(counts)


def stable_digest(label_text):
    """Digest of the label set minus the labels that legitimately change
    every pass: the timestamp, and — under --device-health — the basic
    probe's latency measurement (probe-ms is a fresh wall-clock reading
    per probe, not node identity)."""
    lines = [l for l in label_text.splitlines()
             if not l.startswith("google.com/tfd.timestamp=")
             and not l.startswith("google.com/tpu.health.probe-ms=")]
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


class FileSink:
    """Watches the NFD feature file the daemon rewrites each pass."""

    def __init__(self, tmpdir):
        self.path = os.path.join(tmpdir, "tfd")

    def daemon_args(self):
        return [f"--output-file={self.path}"]

    def daemon_env(self):
        return {}

    def observe(self):
        """(generation, digest) of the current label set; None before the
        first pass. Generation is the file mtime — it advances on every
        rewrite even when the bytes are identical."""
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            return None
        with open(self.path) as f:
            return st.st_mtime, stable_digest(f.read())

    def labels(self):
        """The current label dict, or None before the first pass."""
        try:
            with open(self.path) as f:
                return dict(line.split("=", 1)
                            for line in f.read().splitlines() if line)
        except (OSError, ValueError):
            return None

    def end_state_ok(self):
        return not os.path.exists(self.path)  # SIGTERM removes the file

    def close(self):
        pass


class CrSink:
    """Watches a NodeFeature CR on the hermetic fake apiserver — the
    same steady-state checks, through the real HTTP client path."""

    NODE = "soak-node"

    def __init__(self, tmpdir):
        from tpufd.fakes.apiserver import FakeApiServer

        self.server = FakeApiServer(token="soak-token").__enter__()
        sa = os.path.join(tmpdir, "sa")
        os.mkdir(sa)
        with open(os.path.join(sa, "namespace"), "w") as f:
            f.write("node-feature-discovery\n")
        with open(os.path.join(sa, "token"), "w") as f:
            f.write("soak-token\n")
        self._env = {
            "NODE_NAME": self.NODE,
            "TFD_APISERVER_URL": self.server.url,
            "TFD_SERVICEACCOUNT_DIR": sa,
        }
        self.key = ("node-feature-discovery", f"tfd-features-for-{self.NODE}")

    def daemon_args(self):
        return ["--use-node-feature-api", "--output-file="]

    def daemon_env(self):
        return self._env

    def observe(self):
        obj = self.server.store.get(self.key)
        if obj is None:
            return None
        labels = obj.get("spec", {}).get("labels", {})
        text = "\n".join(f"{k}={v}" for k, v in sorted(labels.items()))
        # Generation = count of CR GETs + PATCHes, not resourceVersion:
        # the timestamp label is constant per config load, so a
        # steady-state pass never bumps rv — and since the fast path, a
        # fingerprint-clean pass skips the CR sink WITHOUT even a GET,
        # so this stream undercounts passes by the daemon's own
        # tfd_sink_writes_skipped_total{sink=cr} (the crosscheck below
        # adds the two). A dirty pass under the diff sink is ONE
        # zero-GET PATCH, so patches count as generations too; GETs
        # cover the first pass and the anti-entropy reconciles. (An
        # anti-entropy reconcile that also finds a diff is GET+PATCH in
        # one pass — rare enough to live inside the crosscheck slack.)
        gen = sum(1 for method, path in list(self.server.requests)
                  if method in ("GET", "PATCH") and self.NODE in path)
        return gen, stable_digest(text)

    def labels(self):
        obj = self.server.store.get(self.key)
        if obj is None:
            return None
        return dict(obj.get("spec", {}).get("labels", {}))

    def end_state_ok(self):
        # The CR persists across daemon restarts by design (NFD owns its
        # lifecycle; the reference leaves its CR too).
        return self.server.store.get(self.key) is not None

    def close(self):
        self.server.__exit__(None, None, None)


# ---- chaos soak (ISSUE 4) --------------------------------------------------
#
# `--chaos` replaces the steady-state soak with a seeded fault schedule
# against the real binary and asserts the invariants that must survive
# ANY schedule: the label file is never torn, /readyz tells the truth,
# injected faults are journaled, the sink breaker opens AND recovers
# with its transitions visible, a kill -9 restart warm-serves the
# persisted state, a torn state file is rejected (not parsed), and
# RSS/fds stay flat. Four phases:
#   1. file sink + injected ENOSPC burst, then kill -9 + warm restart;
#   2. torn state file -> checksum rejection -> clean cold start;
#   3. CR sink + connect-hang + 500-storm -> breaker open -> recovery;
#   4. flap drill (fake_pjrt FLAP_EVERY_N=1): a source whose facts flip
#      every probe must quarantine (tfd_health_state=3) with label
#      churn governed (<=2 changes, suppressions journaled + counted,
#      transitions legal per the tpufd.healthsm twin) and the
#      quarantine restored across a kill -9 warm restart.
# The schedule is deterministic per --chaos-seed (rate draws inside the
# daemon are seeded; counts bound every burst), so CI replays it.


class ChaosDaemon:
    """One daemon launch with the probes the chaos phases share."""

    def __init__(self, binary, argv, env, stderr_path, port):
        self.stderr_path = stderr_path
        self.scraper = MetricsScraper(port)
        with open(stderr_path, "ab") as stderr_file:
            self.proc = subprocess.Popen(
                [binary, *argv], env=env,
                stdout=subprocess.DEVNULL, stderr=stderr_file)

    def wait_first_pass(self, timeout=30):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return False
            gen = self.scraper.generation()
            if gen is not None and gen >= 1:
                return True
            time.sleep(0.05)
        return False

    def journal_events(self):
        doc = self.scraper.get_json("/debug/journal")
        if doc is None:
            return []
        try:
            return tpufd_journal.parse_journal(doc)["events"]
        except ValueError:
            return []

    def stderr_tail(self):
        try:
            with open(self.stderr_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - 500))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def kill9(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait()

    def terminate(self, timeout=30):
        if self.proc.poll() is not None:
            return False
        self.proc.send_signal(signal.SIGTERM)
        try:
            return self.proc.wait(timeout=timeout) == 0
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
            return False


def label_file_torn(path):
    """Returns a problem string if the label file is torn/half-written
    (the never-torn invariant: atomic rename means a reader sees either
    a complete previous file or a complete new one), else None."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return None  # absent is fine (pre-first-pass, post-shutdown)
    except OSError as e:
        return f"label file unreadable: {e}"
    if not data:
        return "label file empty"
    if not data.endswith(b"\n"):
        return "label file does not end in a newline (torn write)"
    for line in data.decode(errors="replace").splitlines():
        if line and "=" not in line:
            return f"label file line without '=': {line!r}"
    return None


def run_chaos(args):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fixture = os.path.join(repo, "tests", "fixtures", "v2-8.yaml")
    seed = args.chaos_seed
    interval = args.interval
    out = {"ok": False, "chaos_seed": seed, "phases": {}}
    problems = []

    def finish():
        out["problems"] = problems or None
        out["ok"] = not problems
        print(json.dumps(out))
        return 0 if out["ok"] else 1

    with tempfile.TemporaryDirectory() as d:
        label_path = os.path.join(d, "tfd")
        state_path = os.path.join(d, "state")
        stderr_path = os.path.join(d, "stderr")
        port = free_loopback_port()
        env = {**os.environ, "GCE_METADATA_HOST": "127.0.0.1:1"}
        base_argv = [f"--sleep-interval={interval}s", "--backend=mock",
                     # The soak derives passes from the per-interval
                     # cadence; the event core is soaked separately
                     # (fleet_soak --watch, tests/test_watch.py).
                     "--event-driven=false",
                     f"--mock-topology-file={fixture}",
                     "--machine-type-file=/dev/null",
                     f"--output-file={label_path}",
                     f"--state-file={state_path}",
                     f"--introspection-addr=127.0.0.1:{port}"]

        # ---- phase 1: ENOSPC burst on the file sink, then kill -9 ----
        phase = {"name": "enospc+warm-restart"}
        fault = (f"sink.file:errno=ENOSPC:rate=0.6:count=5:seed={seed}")
        daemon = ChaosDaemon(args.binary, base_argv +
                             [f"--fault-spec={fault}"], env, stderr_path,
                             port)
        phase_s = max(8.0, min(20.0, args.duration * 0.4))
        if not daemon.wait_first_pass():
            problems.append("phase1: no first pass: " + daemon.stderr_tail())
            daemon.terminate()
            out["phases"]["1"] = phase
            return finish()
        baseline_rss = baseline_fd = None
        saw_unready = False
        deadline = time.monotonic() + phase_s
        while time.monotonic() < deadline:
            if daemon.proc.poll() is not None:
                problems.append("phase1: daemon died: " +
                                daemon.stderr_tail())
                break
            torn = label_file_torn(label_path)
            if torn:
                problems.append(f"phase1: {torn}")
                break
            status = daemon.scraper.readyz()
            if status == 503:
                # /readyz truthfulness, unready direction: 503 must have
                # a visible cause — a recorded rewrite failure.
                failures = daemon.scraper.counter(
                    "tfd_rewrite_failures_total")
                if not failures:
                    problems.append("phase1: /readyz 503 with no recorded "
                                    "rewrite failure (untruthful)")
                    break
                saw_unready = True
            if baseline_rss is None and \
                    (daemon.scraper.generation() or 0) >= 3:
                try:
                    baseline_rss = rss_kb(daemon.proc.pid)
                    baseline_fd = fd_count(daemon.proc.pid)
                except (OSError, RuntimeError):
                    pass
            time.sleep(0.1)
        injected = daemon.scraper.counter(
            "tfd_faults_injected_total{point=sink.file}")
        phase["faults_injected"] = injected
        if not injected:
            problems.append("phase1: no sink.file faults injected "
                            "(schedule never fired)")
        if not saw_unready:
            problems.append("phase1: injected sink failures never surfaced "
                            "on /readyz (untruthful ready)")
        events = daemon.journal_events()
        if not tpufd_journal.fault_injections(events):
            problems.append("phase1: no fault-injected journal events")
        # Recovery: the burst is count-bounded, so the daemon must end
        # the phase ready (faults exhausted, writes landing again).
        recovered = False
        recovery_deadline = time.monotonic() + 4 * interval + 5
        while time.monotonic() < recovery_deadline:
            if daemon.scraper.readyz() == 200:
                recovered = True
                break
            time.sleep(0.2)
        if not recovered:
            problems.append("phase1: /readyz never recovered after the "
                            "count-bounded fault burst")
        passes_before = daemon.scraper.generation() or 0
        phase["passes_before_kill"] = passes_before
        if passes_before < 3:
            problems.append(f"phase1: only {passes_before} passes; cadence "
                            "did not survive the faults")
        if baseline_rss is not None:
            try:
                end_rss = rss_kb(daemon.proc.pid)
                end_fd = fd_count(daemon.proc.pid)
                phase["rss_drift_kb"] = end_rss - baseline_rss
                if end_rss - baseline_rss > args.max_rss_drift_kb:
                    problems.append("phase1: RSS drift "
                                    f"{end_rss - baseline_rss}kb")
                if end_fd > baseline_fd:
                    problems.append(f"phase1: fd growth {baseline_fd}->"
                                    f"{end_fd}")
            except (OSError, RuntimeError):
                problems.append("phase1: daemon died during sampling")
        out["phases"]["1"] = phase

        # ---- kill -9, warm restart (no faults armed) ----
        phase = {"name": "warm-restart"}
        daemon.kill9()
        t0 = time.monotonic()
        daemon = ChaosDaemon(args.binary, base_argv, env, stderr_path, port)
        if not daemon.wait_first_pass():
            problems.append("restart: no pass after kill -9: " +
                            daemon.stderr_tail())
        # Wall bound on kill-to-serving (spawn + config + warm pass);
        # the strict <100ms bound on the warm PASS itself is asserted
        # from the journal below and in tests/test_fault.py.
        phase["restart_to_serve_s"] = round(time.monotonic() - t0, 2)
        if phase["restart_to_serve_s"] > 5.0:
            problems.append("restart: kill-to-serving took "
                            f"{phase['restart_to_serve_s']}s")
        events = daemon.journal_events()
        warm = tpufd_journal.events_of_type(events, "warm-restart")
        if not warm:
            problems.append("restart: no warm-restart journal event "
                            "(state file not served)")
        else:
            fields = warm[0]["fields"]
            phase["warm_ms"] = fields.get("duration_ms")
            phase["warm_labels"] = fields.get("labels")
            if fields.get("ok") != "true":
                problems.append("restart: warm-restart pass failed: "
                                f"{fields}")
            elif int(fields.get("duration_ms", "9999")) > 1000:
                problems.append("restart: warm pass took "
                                f"{fields.get('duration_ms')}ms")
        torn = label_file_torn(label_path)
        if torn:
            problems.append(f"restart: {torn}")
        out["phases"]["warm"] = phase

        # ---- phase 2: torn state file is rejected, not parsed ----
        phase = {"name": "torn-state"}
        daemon.terminate()
        daemon = ChaosDaemon(
            args.binary, base_argv + ["--fault-spec=state.write:torn"],
            env, stderr_path, port)
        if not daemon.wait_first_pass():
            problems.append("phase2: no pass with torn-state fault: " +
                            daemon.stderr_tail())
        time.sleep(2 * interval)  # at least one (torn) state save
        daemon.kill9()
        daemon = ChaosDaemon(args.binary, base_argv, env, stderr_path, port)
        if not daemon.wait_first_pass():
            problems.append("phase2: no cold pass after torn state: " +
                            daemon.stderr_tail())
        events = daemon.journal_events()
        rejected = tpufd_journal.events_of_type(events, "state-rejected")
        if not rejected:
            problems.append("phase2: torn state file was not rejected")
        elif "torn or corrupt" not in rejected[0]["fields"].get("error", ""):
            problems.append("phase2: rejection reason is not the checksum "
                            f"gate: {rejected[0]['fields']}")
        if tpufd_journal.events_of_type(events, "warm-restart"):
            problems.append("phase2: warm-served a TORN state file")
        phase["rejected"] = bool(rejected)
        clean = daemon.terminate()
        if not clean:
            problems.append("phase2: SIGTERM exit was not clean")
        out["phases"]["2"] = phase

        # ---- phase 3: CR sink, connect-hang + 500-storm, breaker ----
        phase = {"name": "breaker"}
        sink = CrSink(d)
        port3 = free_loopback_port()
        stderr3 = os.path.join(d, "stderr3")
        env3 = {**env, **sink.daemon_env()}
        fault = (f"k8s.connect:hang=1500ms:count=2,"
                 f"k8s.get:http=500:count=4:seed={seed}")
        daemon = ChaosDaemon(
            args.binary,
            [f"--sleep-interval={interval}s", "--backend=mock",
             "--event-driven=false",
             # This drill's seeded fault schedule targets the GET-path
             # fault points (the legacy write flow); under server-side
             # apply the write never GETs, so the injected k8s.get 500s
             # would never fire and the breaker would never open.
             "--sink-apply=false",
             f"--mock-topology-file={fixture}",
             "--machine-type-file=/dev/null", *sink.daemon_args(),
             f"--introspection-addr=127.0.0.1:{port3}",
             "--sink-breaker-failures=2", "--sink-breaker-cooldown=3s",
             f"--fault-spec={fault}"],
            env3, stderr3, port3)
        try:
            if not daemon.wait_first_pass():
                problems.append("phase3: no first pass: " +
                                daemon.stderr_tail())
            max_state = 0
            recovered = False
            deadline = time.monotonic() + max(25.0, args.duration)
            while time.monotonic() < deadline:
                if daemon.proc.poll() is not None:
                    problems.append("phase3: daemon died: " +
                                    daemon.stderr_tail())
                    break
                state = daemon.scraper.counter("tfd_sink_breaker_state")
                if state is not None:
                    max_state = max(max_state, int(state))
                if max_state == 2 and state == 0 and \
                        daemon.scraper.readyz() == 200:
                    recovered = True
                    break
                time.sleep(0.2)
            phase["breaker_max_state"] = max_state
            if max_state < 2:
                problems.append("phase3: breaker never opened under the "
                                "500-storm")
            if not recovered:
                problems.append("phase3: breaker never recovered to closed "
                                "+ ready")
            events = daemon.journal_events()
            transitions = tpufd_journal.breaker_transitions(events)
            phase["breaker_transitions"] = transitions or None
            if ("closed", "open") not in transitions:
                problems.append("phase3: closed->open transition not "
                                "journaled")
            if not any(to == "closed" for _, to in transitions):
                problems.append("phase3: recovery to closed not journaled")
            # Cadence survived: the breaker skips instantly, so passes
            # kept ticking even while the apiserver was "down".
            passes = daemon.scraper.generation() or 0
            phase["passes"] = passes
            if passes < 5:
                problems.append(f"phase3: only {passes} passes; the storm "
                                "stalled the rewrite cadence")
            if not daemon.terminate():
                problems.append("phase3: SIGTERM exit was not clean")
        finally:
            if daemon.proc.poll() is None:
                daemon.proc.kill()
                daemon.proc.wait()
            sink.close()
        out["phases"]["3"] = phase

        # ---- phase 4: flap drill — governor + quarantine + restart ----
        phase = {"name": "flap-governor"}
        fake_pjrt = os.path.join(os.path.dirname(
            os.path.abspath(args.binary)), "libtfd_fake_pjrt.so")
        if not os.path.exists(fake_pjrt):
            phase["skipped"] = f"no fake PJRT plugin at {fake_pjrt}"
            out["phases"]["4"] = phase
            return finish()
        from tpufd import healthsm as healthsm_lib

        label4 = os.path.join(d, "tfd4")
        state4 = os.path.join(d, "state4")
        port4 = free_loopback_port()
        stderr4 = os.path.join(d, "stderr4")
        env4 = {**env,
                "TFD_FAKE_PJRT_FLAP_EVERY_N": "1",
                "TFD_FAKE_PJRT_COUNT_FILE": os.path.join(d, "creates4"),
                "TFD_FAKE_PJRT_KIND": "TPU v5 lite",
                "TFD_FAKE_PJRT_BOUNDS": "2,2,1"}
        argv4 = [f"--sleep-interval={interval}s", "--backend=pjrt",
                 "--event-driven=false",
                 f"--libtpu-path={fake_pjrt}",
                 "--pjrt-refresh-interval=0", "--pjrt-retry-backoff=0",
                 "--pjrt-init-timeout=10s",
                 "--machine-type-file=/dev/null",
                 "--snapshot-usable-for=120s",
                 f"--output-file={label4}", f"--state-file={state4}",
                 f"--health-flap-window={10 * interval}s",
                 "--health-flap-threshold=5",
                 f"--quarantine-cooldown={3 * interval}s",
                 f"--introspection-addr=127.0.0.1:{port4}"]
        daemon = ChaosDaemon(args.binary, argv4, env4, stderr4, port4)

        def governed_labels():
            try:
                with open(label4) as f:
                    labels = dict(line.split("=", 1)
                                  for line in f.read().splitlines() if line)
            except (OSError, ValueError):
                return None
            labels.pop("google.com/tfd.timestamp", None)
            labels.pop("google.com/tpu.health.probe-ms", None)
            return labels

        def health_state():
            status, text = daemon.scraper._get("/metrics")
            if status != 200:
                return None
            try:
                return tpufd_metrics.sample_value(
                    text, "tfd_health_state", labels={"source": "pjrt"})
            except ValueError:
                return None

        try:
            if not daemon.wait_first_pass():
                problems.append("phase4: no first pass: " +
                                daemon.stderr_tail())
            observed = []
            deadline = time.monotonic() + max(30.0, 25 * interval)
            quarantined = False
            while time.monotonic() < deadline:
                if daemon.proc.poll() is not None:
                    problems.append("phase4: daemon died: " +
                                    daemon.stderr_tail())
                    break
                labels = governed_labels()
                if labels and (not observed or observed[-1] != labels):
                    observed.append(labels)
                if health_state() == 3 and labels and labels.get(
                        "google.com/tpu.health.quarantined") == "true":
                    quarantined = True
                    # A few more passes to prove the held set is steady.
                    time.sleep(4 * interval)
                    labels = governed_labels()
                    if labels and observed[-1] != labels:
                        observed.append(labels)
                    break
                time.sleep(0.1)
            phase["label_changes"] = len(observed) - 1
            phase["quarantined"] = quarantined
            if not quarantined:
                problems.append("phase4: flapping source never quarantined")
            if len(observed) - 1 > 2:
                problems.append(
                    f"phase4: {len(observed) - 1} label changes under the "
                    "flap (governor budget is 2)")
            # Suppressions: probes and rewrites interleave freely, so the
            # quarantine can engage before any flipped snapshot reaches a
            # rewrite — zero suppressions then just means the hold did
            # all the damping. The journal and the counter must agree.
            events = daemon.journal_events()
            suppressions = healthsm_lib.flap_suppressions(events)
            phase["suppressions"] = len(suppressions)
            suppressed_total = daemon.scraper.counter(
                "tfd_label_flaps_suppressed_total"
                "{key_prefix=google.com/tpu}")
            if suppressions and not suppressed_total:
                problems.append("phase4: flap-suppressed journaled but "
                                "tfd_label_flaps_suppressed_total never "
                                "incremented")
            if suppressed_total and not suppressions:
                problems.append("phase4: tfd_label_flaps_suppressed_total "
                                "incremented without journaled "
                                "flap-suppressed events")
            illegal = healthsm_lib.illegal_transitions(events)
            if illegal:
                problems.append(f"phase4: illegal health transitions "
                                f"journaled: {illegal}")

            # kill -9: the quarantine must ride the state file back.
            daemon.kill9()
            daemon = ChaosDaemon(
                args.binary, argv4 + ["--fault-spec=probe.pjrt:hang=60s"],
                env4, stderr4, port4)
            restored = False
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if tpufd_journal.events_of_type(daemon.journal_events(),
                                                "health-restored") and \
                        health_state() == 3:
                    restored = True
                    break
                time.sleep(0.2)
            phase["quarantine_restored"] = restored
            if quarantined and not restored:
                problems.append("phase4: quarantine did not survive the "
                                "kill -9 warm restart")
            if not daemon.terminate():
                problems.append("phase4: SIGTERM exit was not clean")
        finally:
            if daemon.proc.poll() is None:
                daemon.proc.kill()
                daemon.proc.wait()
        out["phases"]["4"] = phase

    return finish()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--binary", default="build/tpu-feature-discovery")
    ap.add_argument("--duration", type=float, default=30.0,
                    help="seconds to soak")
    ap.add_argument("--interval", type=int, default=1,
                    help="daemon --sleep-interval in seconds")
    ap.add_argument("--sink", choices=["file", "cr"], default="file",
                    help="file: watch the NFD feature file; cr: fake "
                         "apiserver + NodeFeature CR (passes counted "
                         "from the request stream — steady-state passes "
                         "are no-op GETs that never bump resourceVersion)")
    ap.add_argument("--max-rss-drift-kb", type=int, default=1024,
                    help="fail if RSS grows more than this over the soak")
    ap.add_argument("--settle-passes", type=int, default=3,
                    help="passes to let allocators warm up before the RSS "
                         "baseline is taken (first passes legitimately "
                         "grow the heap: stdio buffers, metadata caches)")
    ap.add_argument("--extra-arg", action="append", default=[],
                    help="extra daemon flag (repeatable)")
    ap.add_argument("--require-counter", action="append", default=[],
                    metavar="NAME:MIN",
                    help="fail unless the scraped counter NAME ends the "
                         "soak >= MIN (repeatable) — e.g. "
                         "tfd_pjrt_cache_refreshes_total:2 proves the "
                         "soak crossed a snapshot-cache expiry boundary")
    ap.add_argument("--require-journal", action="store_true",
                    help="enforce the flight-recorder explainability "
                         "invariant: every observed label change has a "
                         "matching journal label-diff event with "
                         "provenance, every observed degradation level "
                         "was journaled as a transition, /debug/labels "
                         "agrees with the label file byte-for-byte, and "
                         "the journal stays within its capacity. Label "
                         "CHURN is allowed (and expected under injected "
                         "wedges) as long as every change is explained — "
                         "labels_stable becomes informational")
    ap.add_argument("--init-grace", type=float, default=180.0,
                    help="seconds allowed for the FIRST pass (backend "
                         "init: a cold PJRT chip claim can take tens of "
                         "seconds); the soak clock starts at the first "
                         "observed rewrite, not at spawn")
    ap.add_argument("--chaos", action="store_true",
                    help="run the seeded chaos schedule instead of the "
                         "steady-state soak: ENOSPC burst + kill -9 warm "
                         "restart, torn-state rejection, and a CR-sink "
                         "connect-hang/500-storm driving the circuit "
                         "breaker open and back — asserting the label "
                         "file is never torn, /readyz stays truthful, "
                         "every fault is journaled, and RSS/fds stay flat")
    ap.add_argument("--chaos-seed", type=int, default=42,
                    help="seed for the chaos schedule's rate draws "
                         "(deterministic replay in CI)")
    args = ap.parse_args(argv)
    if args.chaos:
        return run_chaos(args)

    out = {"ok": False, "sink": args.sink}
    with tempfile.TemporaryDirectory() as d:
        sink = (CrSink if args.sink == "cr" else FileSink)(d)
        stderr_path = os.path.join(d, "stderr")
        # Pass counting scrapes the daemon's own introspection server;
        # a caller-pinned address (--extra-arg=--introspection-addr=...)
        # is scraped too when its port is parseable, so a harness that
        # wants to watch the same daemon (e.g. to inject a wedge at a
        # chosen ladder state) can share the port.
        extra = list(args.extra_arg)
        scraper = None
        pinned = [a for a in extra
                  if a.startswith("--introspection-addr")]
        if pinned:
            pinned_port = pinned[-1].rpartition(":")[2]
            if pinned_port.isdigit() and int(pinned_port) > 0:
                scraper = MetricsScraper(int(pinned_port))
        else:
            port = free_loopback_port()
            extra.append(f"--introspection-addr=127.0.0.1:{port}")
            scraper = MetricsScraper(port)
        cmd = [args.binary, f"--sleep-interval={args.interval}s",
               "--event-driven=false",  # cadence-shaped assertions
               *sink.daemon_args(),
               "--machine-type-file=/dev/null", *extra]
        env = {**os.environ, **sink.daemon_env()}
        env.setdefault("GCE_METADATA_HOST", "127.0.0.1:1")

        def stderr_tail():
            try:
                with open(stderr_path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    f.seek(max(0, f.tell() - 500))
                    return f.read().decode(errors="replace")
            except OSError:
                return ""

        # stderr goes to a file, not a pipe: a chatty daemon on a long
        # soak would fill a 64KB pipe nobody drains and block mid-pass —
        # reading as a false cadence stall.
        with open(stderr_path, "wb") as stderr_file:
            try:
                proc = subprocess.Popen(cmd, env=env,
                                        stdout=subprocess.DEVNULL,
                                        stderr=stderr_file)
            except OSError as e:  # missing/unexecutable binary
                sink.close()
                out["error"] = f"cannot launch {cmd[0]}: {e}"
                print(json.dumps(out))
                return 1
        try:
            digests = set()
            gens, seen_at = [], []
            # --require-journal bookkeeping: full label dicts + scraped
            # degradation levels per observed pass, and the journal
            # accumulated across scrapes (merged by seq, so a wrapped
            # ring never loses what an earlier scrape saw).
            label_history, level_history = [], []
            journal_events, journal_problems = {}, []
            baseline_rss = baseline_fd = None
            gen_source = None  # "metrics" once the scrape works, else sink
            # The soak duration is steady-state time: the clock starts at
            # the FIRST observed rewrite. Spawn-to-first-pass gets its own
            # budget (--init-grace) so slow chip init neither eats the
            # soak nor lets a never-writing daemon hang the harness.
            deadline = time.monotonic() + args.init_grace
            scrape_grace_until = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    break
                # Generations come from the daemon's own rewrite counter;
                # the sink is still read every new generation for the
                # label digest. The source latches on first evidence:
                # a successful scrape wins (the real daemon's server is
                # up before its first pass completes); a sink generation
                # appearing while the scrape still fails past a short
                # grace means a binary without the introspection server
                # (the harness-failure fakes) and latches the legacy
                # sink path. The grace matters under load: a slow first
                # scrape racing an already-written sink must not demote
                # a metrics-capable daemon (which would silently skip
                # the counter/tier checks).
                if gen_source is None:
                    if scraper is not None and \
                            scraper.generation() is not None:
                        gen_source = "metrics"
                    elif sink.observe() is not None and (
                            scraper is None or
                            time.monotonic() >= scrape_grace_until):
                        gen_source = "sink"
                    else:
                        time.sleep(0.05)
                        continue
                if gen_source == "metrics":
                    gen = scraper.generation()
                    if gen is None or gen < 1:  # no pass yet (or hiccup)
                        time.sleep(0.05)
                        continue
                    observed = sink.observe()
                    digest = observed[1] if observed else None
                else:
                    observed = sink.observe()
                    if observed is None:  # first pass not done yet
                        time.sleep(0.05)
                        continue
                    gen, digest = observed
                if not gens or gen != gens[-1]:
                    if not gens:
                        deadline = time.monotonic() + args.duration
                    gens.append(gen)
                    seen_at.append(time.monotonic())
                    if digest is not None:
                        digests.add(digest)
                    if args.require_journal and gen_source == "metrics":
                        labels_now = sink.labels()
                        if labels_now is not None and (
                                not label_history or
                                label_history[-1] != labels_now):
                            label_history.append(labels_now)
                        level = scraper.counter(
                            "tfd_probe_degradation_level")
                        if level is not None and (
                                not level_history or
                                level_history[-1] != level):
                            level_history.append(level)
                        doc = scraper.get_json("/debug/journal")
                        if doc is not None:
                            try:
                                tpufd_journal.merge_events(
                                    journal_events,
                                    tpufd_journal.parse_journal(doc))
                            except ValueError as e:
                                journal_problems.append(str(e))
                    if len(gens) == args.settle_passes:
                        try:
                            baseline_rss = rss_kb(proc.pid)
                            baseline_fd = fd_count(proc.pid)
                        except (OSError, RuntimeError):
                            break  # died mid-sample; poll() below reports
                time.sleep(0.05)

            if proc.poll() is not None:
                out["error"] = (f"daemon died mid-soak rc={proc.returncode}: "
                                f"{stderr_tail()}")
                print(json.dumps(out))
                return 1
            if not gens:
                out["error"] = (f"no first pass within --init-grace="
                                f"{args.init_grace}s: {stderr_tail()}")
                print(json.dumps(out))
                return 1

            try:
                end_rss, end_fd = rss_kb(proc.pid), fd_count(proc.pid)
            except (OSError, RuntimeError):  # died between poll and read
                out["error"] = ("daemon died during final sampling: "
                                + stderr_tail())
                print(json.dumps(out))
                return 1
            # Readiness at soak end: a healthy steady state must also
            # LOOK healthy to the deployment's readiness probe.
            readyz_ok = None
            if gen_source == "metrics":
                readyz_ok = scraper.readyz() == 200
            # Re-probe floors (--require-counter): the cache-expiry
            # soak's proof that snapshot refreshes / health re-execs
            # actually happened, from the daemon's own counters.
            counters_ok = None
            counters = {}
            if args.require_counter and gen_source == "metrics":
                counters_ok = True
                for spec in args.require_counter:
                    name, _, floor = spec.rpartition(":")
                    value = scraper.counter(name)
                    counters[name] = value
                    if value is None or value < float(floor):
                        counters_ok = False
            # Per-source snapshot tiers at soak end, classified with the
            # same policy vocabulary the daemon registers
            # (tpufd.sched mirrors sched/sources.cc): every source of a
            # healthy soak must end fresh.
            snapshot_tiers = None
            if gen_source == "metrics":
                from tpufd import sched as sched_lib

                ages = scraper.by_source("tfd_snapshot_age_seconds")
                policy = sched_lib.device_policy(args.interval)
                snapshot_tiers = {source: sched_lib.tier_of(age, policy)
                                  for source, age in sorted(ages.items())}
            # CR cross-check (cr sink + scraping): every pass must be
            # accounted for server-side as a GET (first pass,
            # anti-entropy reconcile) or a zero-GET diff PATCH — or
            # explained by the daemon's own skip counter: a
            # fingerprint-clean fast pass no-ops the CR sink WITHOUT a
            # request, which is the point of the sub-millisecond steady
            # state (a 50k-node fleet must not hammer the apiserver
            # with no-op reads). Requests + skips must agree with the
            # pass count, within an edge pass.
            crosscheck_ok = None
            if args.sink == "cr" and gen_source == "metrics":
                observed = sink.observe()
                cr_gets = observed[0] if observed else 0
                out["cr_gets"] = cr_gets
                skips = scraper.counter(
                    "tfd_sink_writes_skipped_total{sink=cr}") or 0
                out["cr_writes_skipped"] = skips
                crosscheck_ok = abs(cr_gets + skips - len(gens)) <= 2
            # Flight-recorder invariant (--require-journal), checked
            # while the daemon is still alive: every observed label
            # change explained by a provenance-carrying label-diff
            # event, every observed degradation level journaled as a
            # transition target, /debug/labels byte-identical to the
            # emitted label file, journal within capacity.
            journal_ok = None
            if args.require_journal and gen_source != "metrics":
                # Requiring the invariant without a scrape path must fail
                # loudly, not silently skip every check.
                journal_ok = False
                out["journal_problems"] = [
                    "--require-journal needs the metrics scrape path "
                    f"(gen_source={gen_source}); pin a scrapeable "
                    "--introspection-addr or drop the pin"]
            if args.require_journal and gen_source == "metrics":
                # Labels BEFORE the journal: a rewrite landing between
                # the two reads must be covered by the scraped events,
                # which holds only when the label observation is the
                # earlier one (the in-loop scrape uses the same order).
                labels_now = sink.labels()
                if labels_now is not None and (
                        not label_history or
                        label_history[-1] != labels_now):
                    label_history.append(labels_now)
                doc = scraper.get_json("/debug/journal")
                if doc is not None:
                    try:
                        tpufd_journal.merge_events(
                            journal_events,
                            tpufd_journal.parse_journal(doc))
                    except ValueError as e:
                        journal_problems.append(str(e))
                if not journal_events:
                    journal_problems.append("no journal events scraped")
                changes = []
                for prev, cur in zip(label_history, label_history[1:]):
                    changes.extend(tpufd_journal.label_changes(prev, cur))
                _, cover_problems = tpufd_journal.diffs_cover_changes(
                    journal_events, changes)
                journal_problems.extend(cover_problems)
                transitions = tpufd_journal.degradation_transitions(
                    journal_events)
                journaled_to = {t for _, t in transitions}
                for level in sorted({str(int(lv)) for lv in level_history
                                     if lv is not None}):
                    if level not in journaled_to:
                        journal_problems.append(
                            f"observed degradation level {level} never "
                            "journaled as a transition")
                if args.sink == "file":
                    # Byte-for-byte agreement, retried around the race
                    # with an in-flight rewrite: only an observation
                    # where the file did not change while /debug/labels
                    # was fetched counts.
                    agreed = False
                    for _ in range(5):
                        try:
                            with open(sink.path) as f:
                                before = f.read()
                        except OSError:
                            before = None
                        debug_labels = scraper.get_json("/debug/labels")
                        try:
                            with open(sink.path) as f:
                                after = f.read()
                        except OSError:
                            after = None
                        if (before is not None and before == after
                                and debug_labels is not None
                                and tpufd_journal.labels_file_text(
                                    debug_labels) == before):
                            agreed = True
                            break
                        # Mismatch with a stable file still retries: the
                        # daemon writes the file, THEN hands the endpoint
                        # its document — a sample in that window sees the
                        # endpoint one rewrite behind.
                        time.sleep(0.2)
                    if not agreed:
                        journal_problems.append(
                            "/debug/labels does not match the emitted "
                            "label file byte-for-byte")
                journal_ok = not journal_problems
                out["journal_events"] = len(journal_events)
                out["journal_label_changes"] = len(changes)
                out["journal_degradations"] = transitions or None
                out["journal_problems"] = journal_problems or None
            proc.send_signal(signal.SIGTERM)
            try:
                clean = proc.wait(timeout=30) == 0
            except subprocess.TimeoutExpired:
                clean = False  # won't shut down IS the finding
            gaps = sorted(b - a for a, b in zip(seen_at, seen_at[1:]))
            p50 = round(gaps[len(gaps) // 2], 2) if gaps else None
            # Cadence is part of ok (advisor r5): a daemon that settles
            # then stalls for the rest of the soak must not report
            # steady. Both halves: enough passes for the wall time, and
            # a p50 rewrite interval in the right ballpark.
            cadence_ok = (
                len(gens) >= max(3, int(0.5 * args.duration / args.interval))
                and (p50 is None or p50 <= 3 * args.interval))

            out.update({
                "passes": len(gens),
                "gen_source": gen_source,
                "rss_start_kb": baseline_rss, "rss_end_kb": end_rss,
                "rss_drift_kb": (None if baseline_rss is None
                                 else end_rss - baseline_rss),
                "fd_start": baseline_fd, "fd_end": end_fd,
                "labels_stable": len(digests) == 1,
                "rewrite_interval_p50_s": p50,
                "cadence_ok": cadence_ok,
                "readyz_ok": readyz_ok,
                "crosscheck_ok": crosscheck_ok,
                "counters": counters or None,
                "counters_ok": counters_ok,
                "snapshot_tiers": snapshot_tiers,
                "journal_ok": journal_ok,
                "clean_exit": clean,
                "end_state_ok": sink.end_state_ok(),
            })
            # Under --require-journal, label churn is allowed as long as
            # every change is journal-explained (an injected wedge SHOULD
            # change labels); otherwise stability is required as before.
            labels_accounted = out["labels_stable"] or (
                args.require_journal and journal_ok is True)
            out["ok"] = bool(
                len(gens) >= max(3, args.settle_passes)
                and cadence_ok
                and readyz_ok is not False
                and crosscheck_ok is not False
                and counters_ok is not False
                and journal_ok is not False
                and baseline_rss is not None
                and out["rss_drift_kb"] <= args.max_rss_drift_kb
                # A leak is monotone GROWTH; ending below the baseline
                # just means the baseline sample caught a transient
                # probe-worker fd (min-of-3 narrows but cannot close
                # that window).
                and end_fd <= baseline_fd
                and labels_accounted and clean
                and out["end_state_ok"])
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            sink.close()
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Multi-host slice-coherence chaos soak (ISSUE 10 acceptance).

Boots N (default 4) REAL daemons as the member hosts of one fake slice —
every process loads the fake PJRT plugin with the SAME global topology
(TFD_FAKE_PJRT_BOUNDS) and its own host index (TFD_FAKE_PJRT_PROC), all
coordinating through ONE fake apiserver (each host on its own listener
so a single host can be network-partitioned) — then walks a seeded chaos
schedule:

  kill-follower     SIGKILL a non-leader member        -> survivors 3/4
  restart           bring it back                      -> 4/4
  crash-loop-dwell  kill/restart it twice inside the   -> healthy-hosts
                    rejoin dwell (--slice-rejoin-dwell)   NEVER flaps up
                                                          per restart;
                                                          re-counted only
                                                          after it stays
                                                          up through the
                                                          dwell
  kill-leader       SIGKILL the lease holder           -> failover + 3/4
  restart           bring it back                      -> 4/4
  wedge-pjrt        wedge one member's PJRT (hang file)-> 3/4 everywhere
  unwedge           lift the wedge                     -> 4/4
  preempt-notice    flip one member's GCE              -> the leader folds
                    instance/preempted to TRUE            the still-alive
                    (its own fake metadata server)        member into a
                                                          proactive 3/4
                                                          degraded verdict
  preempt-clear     notice cleared                     -> 4/4
  partition         refuse one member's apiserver AND  -> peers probe its
                    freeze the process (SIGSTOP): a       introspection,
                    FULL partition, nothing of the        get no answer,
                    member is reachable                   confirm it stale
                                                          and degrade 3/4
                                                          AHEAD of the
                                                          ageing window
  heal              SIGCONT + restore the listener     -> rejoin, 4/4
  asym-partition    refuse one member's apiserver but  -> peers RELAY its
                    leave the process running: the        live report onto
                    asymmetric partition (member          the blackboard
                    reaches peers, not the apiserver)     (slice-relay):
                                                          the slice NEVER
                                                          degrades; the
                                                          member itself
                                                          self-demotes
                                                          (slice-orphaned)
  asym-degrade      preempt-notice a THIRD member      -> verdict moves to
                    while the victim is still severed     3/4 everywhere;
                                                          cr sink: the
                                                          leader HEDGES
                                                          the verdict onto
                                                          the severed
                                                          member's CR
                                                          (slice-hedge)
  asym-recover      notice cleared                     -> back to 4/4
  asym-heal         restore the victim's listener      -> instant rejoin
                                                          (relay kept it
                                                          continuously
                                                          present: no
                                                          rejoin dwell);
                                                          cr sink: its own
                                                          apply reclaims
                                                          the hedged keys
  brownout-         throttle the apiserver below the   -> the FIRST listed
  succession        fleet's offered load (429s), then     successor takes
                    SIGKILL the lease holder              the lease at the
                                                          first missed
                                                          renewal tick
                                                          (slice-
                                                          succession),
                                                          ahead of lease
                                                          expiry
  brownout-clear    lift the throttle + restart       -> 4/4
  kill9-leader      kill -9 the leader + instant       -> lease resumed
                    restart (same state file)             from the state
                                                          file: NO epoch
                                                          bump, survivors'
                                                          labels never
                                                          move

Invariants asserted at every step:
  - all live hosts' tpu.slice.* labels are BYTE-IDENTICAL once the step
    converges, and the disagreement window (first label movement ->
    convergence) is at most 2 probe intervals;
  - detection latency is bounded by the layer that owns it: the
    agreement timeout for a dead member, the PJRT fresh window for a
    wedged one, the lease duration for a partition;
  - ZERO "interleaved disagreement" samples: outside a step's
    convergence window, no sample may show two live hosts publishing
    different slice labels;
  - the asymmetrically partitioned member drops its slice labels
    entirely (never a stale slice view) and journals slice-orphaned,
    while the slice itself NEVER degrades (peer report relay keeps it
    counted) — and with the cr sink the leader hedges verdict changes
    onto its CR so the scheduler's view never goes stale either;
  - the fully partitioned member is confirmed-stale by a failed peer
    probe and excluded ahead of the agreement-timeout ageing window;
  - the lease moves by pre-declared succession (slice-succession, at
    the first missed renewal tick) when the holder dies mid-brownout;
  - the kill -9'd leader resumes its lease epoch from the state file.

`--json FILE` writes the bench record bench_gate.py --slice gates
against the committed BENCH_r10.json.

`--sink cr` runs the SAME schedule with every member publishing through
the NodeFeature-CR sink (watch + server-side apply against the fake
apiserver) instead of the label file — coherence is then sampled from
the CR store, the bytes a scheduler actually sees. Sole expected delta:
a severed member cannot write its self-demotion (the partition severs
the sink too) — the demotion is asserted via the slice-orphaned
journal, and under the ASYMMETRIC partition the leader's hedged
publishes (--sink-hedge, field manager tfd-hedge) keep its CR on the
agreed verdict instead of letting it go stale.

Usage:
  python3 scripts/slice_soak.py [--hosts 4] [--seed 10] [--json out.json]
      [--sink file|cr]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpufd import slicecoord  # noqa: E402
from tpufd.fakes import free_loopback_port  # noqa: E402
from tpufd.fakes.apiserver import FakeApiServer  # noqa: E402
from tpufd.fakes.metadata_server import FakeMetadataServer  # noqa: E402

BUILD = REPO / "build"
BINARY = BUILD / "tpu-feature-discovery"
FAKE_PJRT = BUILD / "libtfd_fake_pjrt.so"

INTERVAL_S = 1
AGREEMENT_S = 2
LEASE_S = 3
PJRT_TIMEOUT_S = 3
SLICE_ID = "soak-slice"
NS = "slice-soak"


class SoakError(AssertionError):
    pass


def require(cond, message):
    if not cond:
        raise SoakError(message)


class Member:
    def __init__(self, tmp, index, url, hosts, sink_mode="file",
                 cr_store=None, metadata_port=None):
        self.index = index
        self.node = f"soak-host-{index}"
        self.url = url
        self.sink_mode = sink_mode
        self.cr_store = cr_store  # the shared fake-apiserver store
        self.out_file = tmp / f"tfd-{index}"
        self.state_file = tmp / f"state-{index}"
        self.hang_file = tmp / f"hang-{index}"
        self.port = free_loopback_port()
        self.argv = [
            str(BINARY), f"--sleep-interval={INTERVAL_S}s",
            "--event-driven=false",  # cadence-shaped disagreement windows
            "--backend=pjrt", f"--libtpu-path={FAKE_PJRT}",
            f"--pjrt-init-timeout={PJRT_TIMEOUT_S}s",
            "--pjrt-refresh-interval=1s",
            # Failed inits are memoized; the default 60s window would
            # stretch un-wedge recovery far past the step budget.
            "--pjrt-retry-backoff=1s",
            "--machine-type-file=/dev/null",
            f"--output-file={self.out_file}",
            f"--state-file={self.state_file}",
            f"--introspection-addr=127.0.0.1:{self.port}",
            "--slice-coordination",
            f"--slice-lease-duration={LEASE_S}s",
            f"--slice-agreement-timeout={AGREEMENT_S}s",
            # A request in flight when a partition starts can hang to
            # the full deadline; keep it under the lease so ONE stalled
            # tick can't push self-demotion past the step budget.
            "--sink-request-deadline=2s",
            # Every boot's "waiting for the first device probe round"
            # slice-probe error costs 2 healthsm transitions that the
            # state file PERSISTS across restarts; the crash-loop-dwell
            # drill boots the same member 4 times (8 transitions),
            # which at the default threshold of 6 would quarantine its
            # slice source for the default 600s cooldown and wedge the
            # drill. 12 keeps the soak's restart budget under the bar
            # without masking anything the soak asserts (no step ever
            # legitimately quarantines here).
            "--health-flap-threshold=12",
            "--cadence-jitter-pct=0", "--no-timestamp",
            # Preemption fast path (ISSUE 13 satellite): every member
            # watches its own fake metadata server's instance/preempted.
            "--lifecycle-watch",
        ]
        if sink_mode == "cr":
            # The NodeFeature-CR sink variant (PR 9's nuance, closed
            # here): slice labels ride watch+SSA to the apiserver
            # instead of the label file; coherence is then sampled from
            # the CR store — the bytes a scheduler actually sees. The
            # breaker cooldown is shortened so the heal step re-asserts
            # at the protocol's cadence instead of parking the sink for
            # the default 30s after the partition's failed writes.
            self.argv += ["--use-node-feature-api", "--output-file=",
                          "--sink-breaker-cooldown=2s"]
        self.env = {
            **os.environ,
            "GCE_METADATA_HOST": (f"127.0.0.1:{metadata_port}"
                                  if metadata_port else "127.0.0.1:1"),
            "NODE_NAME": self.node,
            "TFD_APISERVER_URL": url,
            "KUBERNETES_NAMESPACE": NS,
            "TFD_SLICE_ID": SLICE_ID,
            "TFD_SLICE_WORKER_ID": str(index),
            "TFD_SLICE_HOSTS": "",  # set by the soak
            "TFD_FAKE_PJRT_KIND": "TPU v5 lite",
            "TFD_FAKE_PJRT_BOUNDS": "4,4,1",
            "TFD_FAKE_PJRT_HOSTS": "",  # set by the soak
            "TFD_FAKE_PJRT_PROC": str(index),
            "TFD_FAKE_PJRT_HANG_IF_FILE": str(self.hang_file),
        }
        self.proc = None

    def start(self):
        # Stderr kept per host (appended across restarts): the chaos
        # post-mortems need the coordinator's own account.
        self.log = open(self.out_file.parent / f"log-{self.index}", "a")
        self.proc = subprocess.Popen(self.argv, env=self.env,
                                     stderr=self.log)

    def kill(self, sig=signal.SIGKILL):
        if self.proc is None:
            return
        self.proc.send_signal(sig)
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            # A SIGSTOPped member (the full-partition drill) ignores
            # SIGTERM until resumed; don't let a failed drill leave a
            # frozen orphan holding the log pipe open.
            self.proc.send_signal(signal.SIGCONT)
            self.proc.kill()
            self.proc.wait(timeout=10)
        self.proc = None

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def full_labels(self):
        if self.sink_mode == "cr":
            obj = self.cr_store.get((NS, f"tfd-features-for-{self.node}"))
            if obj is None:
                return None
            return dict((obj.get("spec") or {}).get("labels") or {})
        try:
            return dict(line.split("=", 1) for line in
                        self.out_file.read_text().splitlines() if line)
        except (OSError, ValueError):
            return None  # unreadable mid-write; sample again

    def slice_labels(self):
        labels = self.full_labels()
        if labels is None:
            return None
        return slicecoord.slice_labels_of(labels)

    def journal_types(self):
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/debug/journal?n=512",
                    timeout=2) as r:
                doc = json.loads(r.read().decode())
            return [e.get("type") for e in doc.get("events", [])]
        except Exception:
            return []

    def metric(self, name):
        """Reads one counter off this member's /metrics exposition
        (0.0 when absent or unreachable)."""
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/metrics",
                    timeout=2) as r:
                text = r.read().decode()
        except Exception:
            return 0.0
        for line in text.splitlines():
            if line.startswith(name) and not line.startswith("#"):
                try:
                    return float(line.rsplit(None, 1)[-1])
                except ValueError:
                    return 0.0
        return 0.0


def expected_labels(sanitized_id, hosts, healthy):
    verdict = {"hosts": hosts, "healthy_hosts": healthy,
               "degraded": healthy < hosts, "class": "", "members": []}
    return slicecoord.build_slice_labels(sanitized_id, verdict)


class Soak:
    def __init__(self, hosts, seed):
        import random
        self.random = random.Random(seed)
        self.hosts = hosts
        self.sanitized_id = slicecoord.sanitize_slice_id(SLICE_ID)
        self.steps = []
        self.interleaved = 0
        self.samples = 0
        # High-water marks for the partition-tolerance counters,
        # captured at the drill that asserted them: a member restarted
        # by a LATER drill (brownout kill, kill -9) boots with zeroed
        # in-process counters, so the end-of-run sum alone can
        # under-count a path that demonstrably fired.
        self.counter_floors = {}

    def note_counter(self, name, value):
        if value > self.counter_floors.get(name, 0):
            self.counter_floors[name] = value

    def sample_all(self, members):
        """One coherence sample across the live members; returns
        {index: labels} for members whose file is readable."""
        out = {}
        for m in members:
            if not m.alive():
                continue
            labels = m.slice_labels()
            if labels is not None:
                out[m.index] = labels
        return out

    def watch_steady(self, members, duration, phase=""):
        """Between steps: no sample may show two live hosts CLAIMING
        different slice facts — the 'interleaved disagreement' the
        acceptance forbids. A host with NO slice labels is abstaining
        (the designed self-demoted/booting state: agreed-or-absent),
        not disagreeing."""
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            sets = [s for s in self.sample_all(members).values() if s]
            self.samples += 1
            if sets and any(s != sets[0] for s in sets[1:]):
                self.interleaved += 1
                if phase:
                    print(f"    DISAGREE[{phase}]: {sets}")
            time.sleep(0.1)

    def settle(self, name, members, want, quiet_s, budget_s):
        """A FATAL quiet gate between drills: every live member must
        hold `want` continuously for quiet_s before the next drill
        starts. converge() can be satisfied by a frozen member's stale
        pre-freeze bytes (its file cannot change) or by a fleet that
        touches the target mid-churn — either way the next drill would
        begin over residual turbulence (rejoins still settling, lease
        churn) and its asserts would blame the wrong protocol."""
        deadline = time.monotonic() + budget_s
        quiet_since = None
        while True:
            sample = self.sample_all(members)
            self.samples += 1
            ok = all(sample.get(m.index) == want
                     for m in members if m.alive())
            now = time.monotonic()
            if ok:
                if quiet_since is None:
                    quiet_since = now
                if now - quiet_since >= quiet_s:
                    return
            else:
                quiet_since = None
            require(now < deadline,
                    f"settle {name}: fleet never quiet for {quiet_s}s "
                    f"within {budget_s}s (sample {sample})")
            time.sleep(0.05)

    def converge(self, name, members, want, budget_s, extra_check=None,
                 enforce_window=True):
        """Waits for every live member's slice labels to equal `want`
        (a dict, or a per-index dict-of-dicts), measuring convergence
        latency and the disagreement window. `budget_s` bounds the
        whole step. `enforce_window` applies the 2-probe-interval
        disagreement bound — the FAILURE-relabeling acceptance; steps
        that include a host booting (join, rejoins) measure but don't
        enforce it, since a cold daemon's settle window is not a
        coherence failure."""
        t0 = time.monotonic()
        first_change = None
        baseline = self.sample_all(members)
        while True:
            now = time.monotonic()
            sample = self.sample_all(members)
            self.samples += 1
            if first_change is None and sample != baseline:
                first_change = now
            def want_of(index):
                if want and isinstance(next(iter(want.values()), None),
                                       dict):
                    return want.get(index, {})
                return want
            done = all(m.alive() is False or
                       sample.get(m.index) == want_of(m.index)
                       for m in members)
            if done and (extra_check is None or extra_check()):
                break
            require(now - t0 < budget_s,
                    f"step {name}: no convergence within {budget_s}s "
                    f"(sample {sample}; extra_check="
                    f"{extra_check() if extra_check else None})")
            time.sleep(0.05)
        t_converged = time.monotonic()
        latency_ms = (t_converged - t0) * 1000
        disagreement_ms = ((t_converged - first_change) * 1000
                           if first_change is not None else 0.0)
        if enforce_window:
            require(disagreement_ms <= 2 * INTERVAL_S * 1000 + 500,
                    f"step {name}: disagreement window "
                    f"{disagreement_ms:.0f}ms exceeds 2 probe intervals")
        self.steps.append({"name": name,
                           "latency_ms": round(latency_ms, 1),
                           "disagreement_ms": round(disagreement_ms, 1)})
        print(f"  step {name}: converged in {latency_ms:.0f}ms "
              f"(disagreement window {disagreement_ms:.0f}ms)")

    def record(self):
        latencies = sorted(s["latency_ms"] for s in self.steps)
        p50 = latencies[len(latencies) // 2] if latencies else None
        return {
            "soak": "slice",
            "hosts": self.hosts,
            "interval_s": INTERVAL_S,
            "agreement_timeout_s": AGREEMENT_S,
            "lease_duration_s": LEASE_S,
            "steps": self.steps,
            "slice_agreement_p50_ms": p50,
            "max_disagreement_ms": max(
                (s["disagreement_ms"] for s in self.steps), default=0),
            "interleaved_disagreement_passes": self.interleaved,
            "coherence_samples": self.samples,
        }


def lease_of(server):
    doc = server.store.get(
        (NS, "tfd-slice-" + slicecoord.sanitize_slice_id(SLICE_ID)))
    raw = (doc or {}).get("data", {}).get("lease")
    return json.loads(raw) if raw else None


def run_soak(hosts, seed, tmp, sink_mode="file"):
    soak = Soak(hosts, seed)
    sid = soak.sanitized_id
    # One fake metadata server per member so the preemption drill can
    # flip ONE host's instance/preempted without touching the others.
    from tpufd.fakes.metadata_server import tpu_vm
    metas = [FakeMetadataServer(tpu_vm(accelerator_type="v5litepod-16",
                                       worker_id=i, preemptible=True))
             for i in range(hosts)]
    for meta in metas:
        meta.__enter__()
    with FakeApiServer() as server:
        listeners = [server.add_listener() for _ in range(hosts)]
        members = [Member(tmp, i, listeners[i].url, hosts,
                          sink_mode=sink_mode, cr_store=server.store,
                          metadata_port=metas[i].port)
                   for i in range(hosts)]
        for m in members:
            m.env["TFD_SLICE_HOSTS"] = str(hosts)
            m.env["TFD_FAKE_PJRT_HOSTS"] = str(hosts)
        try:
            print(f"slice soak: {hosts} hosts, seed {seed}, "
                  f"sink={sink_mode}")
            for m in members:
                m.start()
            # Join: everyone healthy, byte-identical. Cold PJRT probes
            # pay the settle window once.
            soak.converge("join", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=30, enforce_window=False)
            soak.watch_steady(members, 2, phase="w1")

            lease = lease_of(server)
            require(lease is not None, "no lease on the blackboard")
            leader = next(m for m in members if m.node == lease["holder"])
            follower = next(m for m in members if m is not leader)

            # 1. Kill a follower: detection <= agreement timeout, then
            # a 2-interval convergence window.
            follower.kill(signal.SIGKILL)
            soak.converge("kill-follower", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=AGREEMENT_S + 4 * INTERVAL_S + 3)
            soak.watch_steady(members, 2, phase="w2")
            follower.start()
            soak.converge("member-rejoin", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=20, enforce_window=False)
            soak.watch_steady(members, 2, phase="w3")

            # 1b. Crash-loop rejoin hysteresis (ISSUE 11 satellite,
            # --slice-rejoin-dwell at its auto default = 2x the
            # agreement timeout): a member restarting FASTER than the
            # dwell must not flap healthy-hosts back up once per
            # restart — the leader re-counts it only after it stays
            # continuously present through the dwell.
            follower.kill(signal.SIGKILL)
            soak.converge("dwell-depart", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=AGREEMENT_S + 4 * INTERVAL_S + 3)
            follower.start()
            # While the crash-looper is inside its dwell, no SURVIVOR
            # may claim full health — this is the flap the hysteresis
            # exists to prevent. (The restarting member itself is
            # excluded: its on-disk label file legitimately holds the
            # pre-kill bytes until its first warm-restart pass.)
            survivors = [m for m in members if m is not follower]
            flap_deadline = time.monotonic() + 3 * INTERVAL_S
            while time.monotonic() < flap_deadline:
                for index, labels in soak.sample_all(survivors).items():
                    if labels:
                        require(labels[slicecoord.SLICE_HEALTHY_HOSTS]
                                != str(hosts),
                                f"crash-looper re-counted healthy inside "
                                f"its rejoin dwell (healthy-hosts "
                                f"flapped; host {index} published "
                                f"{labels})")
                soak.samples += 1
                time.sleep(0.1)
            # Second crash inside the dwell, then a real recovery: the
            # departure clock refreshes, so full health returns only
            # after the member finally stays up through the dwell.
            follower.kill(signal.SIGKILL)
            follower.start()
            soak.converge("crash-loop-dwell", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=2 * AGREEMENT_S + 25,
                          enforce_window=False)
            # Whichever member held the lease when the crash-looper
            # rejoined journaled the dwell — leadership may have moved
            # since (succession promotes at a missed renewal, and a
            # leader tick stalled on a probe of the mid-restart member
            # can miss one), so scan every live member's journal.
            require(any("slice-rejoin-dwell" in m.journal_types()
                        for m in members if m.alive()),
                    "no member ever journaled slice-rejoin-dwell for "
                    "the crash-looping member")
            soak.watch_steady(members, 2, phase="w3b")

            # 2. Kill the leader: lease failover (epoch bump) + the
            # same coherent degrade on every survivor. Re-resolve the
            # holder first — leadership may have moved since the join.
            lease = lease_of(server)
            leader = next(m for m in members if m.node == lease["holder"])
            epoch_before = lease["epoch"]
            leader.kill(signal.SIGKILL)
            soak.converge(
                "kill-leader", members,
                expected_labels(sid, hosts, hosts - 1),
                budget_s=LEASE_S + AGREEMENT_S + 4 * INTERVAL_S + 3,
                extra_check=lambda: (lease_of(server) or {}).get(
                    "epoch", 0) > epoch_before)
            require(lease_of(server)["holder"] != leader.node,
                    "dead leader still holds the lease")
            soak.watch_steady(members, 2, phase="w4")
            leader.start()
            soak.converge("leader-rejoin", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=20, enforce_window=False)
            soak.watch_steady(members, 2, phase="w5")

            # 3. Wedge one member's PJRT: its device snapshot ages out
            # of fresh, its report turns unhealthy, the SLICE degrades
            # — coherently, on all four LIVE hosts (the wedged one
            # publishes the same agreed verdict).
            wedged = next(m for m in members
                          if m.node != lease_of(server)["holder"])
            wedged.hang_file.touch()
            fresh_window = 4 * INTERVAL_S + PJRT_TIMEOUT_S
            soak.converge("wedge-pjrt", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=fresh_window + AGREEMENT_S +
                          4 * INTERVAL_S + 5)
            soak.watch_steady(members, 2, phase="w6")
            wedged.hang_file.unlink()
            soak.converge("unwedge", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=fresh_window + 10,
                          enforce_window=False)
            soak.watch_steady(members, 2, phase="w7")

            # 3b. Preemption fast path (ISSUE 13 satellite): GCE issues
            # a preemption notice to one member. Its lifecycle source
            # (1s tick here) publishes tpu.lifecycle.preempt-imminent,
            # the report carries preempting=true, and the LEADER folds
            # the still-alive-but-doomed member into a proactive
            # degraded verdict — every host relabels 3/4 coherently
            # BEFORE the VM actually dies.
            lease = lease_of(server)
            doomed = next(m for m in members
                          if m.node != lease["holder"])
            notice = tpu_vm(accelerator_type="v5litepod-16",
                            worker_id=doomed.index, preemptible=True,
                            preempted=True)
            metas[doomed.index].set_data(notice)
            soak.converge("preempt-notice", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=AGREEMENT_S + 6 * INTERVAL_S + 3)
            require("lifecycle-change" in doomed.journal_types(),
                    "preempted member never journaled lifecycle-change")
            doomed_labels = doomed.full_labels() or {}
            require(doomed_labels.get(
                        "google.com/tpu.lifecycle.preempt-imminent")
                    == "true",
                    f"preempted member never published preempt-imminent "
                    f"(labels {doomed_labels})")
            soak.watch_steady(members, 2, phase="w7b")
            # The notice clears (drill ends; in production the VM dies
            # and the kill/restart steps above cover that path).
            metas[doomed.index].set_data(
                tpu_vm(accelerator_type="v5litepod-16",
                       worker_id=doomed.index, preemptible=True))
            soak.converge("preempt-clear", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=AGREEMENT_S + 6 * INTERVAL_S + 3,
                          enforce_window=False)
            soak.watch_steady(members, 2, phase="w7c")
            soak.settle("pre-partition", members,
                        expected_labels(sid, hosts, hosts),
                        quiet_s=2, budget_s=LEASE_S + 10)

            # 4. FULL partition: nothing of the victim is reachable —
            # the apiserver listener refuses AND the process is frozen
            # (SIGSTOP), so the peers' relay probes of its
            # introspection port time out. Confirm-or-relay
            # (--slice-relay) turns that failed probe into a
            # confirmed-stale exclusion AHEAD of the agreement-timeout
            # ageing window: the budget here is tightened below the
            # pre-relay LEASE_S+AGREEMENT_S bound — reduced in source,
            # not waived. The frozen victim's sink holds its pre-freeze
            # bytes (it cannot demote while stopped); the asymmetric
            # drill below owns the self-demotion assertion.
            lease = lease_of(server)
            victim = next(m for m in members
                          if m.node != lease["holder"])
            listeners[victim.index].stop()
            victim.proc.send_signal(signal.SIGSTOP)
            frozen_labels = expected_labels(sid, hosts, hosts)
            want = {m.index: (expected_labels(sid, hosts, hosts - 1)
                              if m is not victim else frozen_labels)
                    for m in members}
            soak.converge("partition", members, want,
                          budget_s=AGREEMENT_S + 4 * INTERVAL_S + 2)
            soak.watch_steady([m for m in members if m is not victim],
                              2, phase="w8")
            victim.proc.send_signal(signal.SIGCONT)
            listeners[victim.index].start()
            soak.converge("heal", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=LEASE_S + 15, enforce_window=False)
            soak.watch_steady(members, 2, phase="w9")
            # heal's converge is satisfiable by the victim's pre-freeze
            # bytes alone (a frozen file still reads 4/4); the asym
            # drill's "never degrades" assert needs the victim actually
            # caught up and the lease churn drained first.
            soak.settle("post-heal", members,
                        expected_labels(sid, hosts, hosts),
                        quiet_s=2, budget_s=LEASE_S + 15)

            # 4b. ASYMMETRIC partition (the ISSUE 19 tentpole): the
            # victim reaches its peers but not the apiserver. Its
            # blackboard report goes stale, a peer probes its
            # introspection addr, gets the live report, and RELAYS it
            # onto the blackboard — the slice must NOT degrade: the
            # relabeling non-event is the acceptance. The victim
            # itself, cut off from the blackboard, still self-demotes
            # (agreed-or-absent is about ITS view, which it cannot
            # refresh).
            lease = lease_of(server)
            victim = next(m for m in members
                          if m.node != lease["holder"])
            survivors = [m for m in members if m is not victim]
            listeners[victim.index].stop()
            relayer = None
            relay_deadline = time.monotonic() + AGREEMENT_S + 6
            while time.monotonic() < relay_deadline and relayer is None:
                for index, labels in soak.sample_all(survivors).items():
                    if labels:
                        require(
                            labels[slicecoord.SLICE_HEALTHY_HOSTS]
                            == str(hosts),
                            f"slice degraded under an ASYMMETRIC "
                            f"partition (host {index} published "
                            f"{labels}); the relay should have kept "
                            f"the severed member counted")
                soak.samples += 1
                relayer = next((m for m in survivors
                                if "slice-relay" in m.journal_types()),
                               None)
                time.sleep(0.1)
            require(relayer is not None,
                    "no peer ever journaled slice-relay for the "
                    "asymmetrically partitioned member")
            relayed_now = relayer.metric("tfd_slice_relayed_reports_total")
            require(relayed_now > 0,
                    "slice-relay journaled but the relayed-reports "
                    "counter never moved")
            soak.note_counter("slice_relayed_reports", relayed_now)
            # The victim's self-demotion: visible in its label file
            # (file sink), or via journal only (cr sink — it cannot
            # write, and the leader's hedge keeps its CR on the agreed
            # verdict rather than letting it go stale).
            victim_want = ({} if sink_mode == "file"
                           else expected_labels(sid, hosts, hosts))
            want = {m.index: (expected_labels(sid, hosts, hosts)
                              if m is not victim else victim_want)
                    for m in members}
            soak.converge("asym-partition", members, want,
                          budget_s=LEASE_S + 4 * INTERVAL_S + 3)
            orphan_deadline = time.monotonic() + LEASE_S + 5
            while (time.monotonic() < orphan_deadline
                   and "slice-orphaned" not in victim.journal_types()):
                time.sleep(0.1)
            require("slice-orphaned" in victim.journal_types(),
                    "asymmetrically partitioned member never journaled "
                    "slice-orphaned")

            # 4c. The verdict MOVES while the victim is severed: a
            # third member gets a preemption notice. Every reachable
            # member relabels 3/4 — and with the CR sink the leader
            # HEDGES the new verdict onto the severed member's CR
            # under the tfd-hedge field manager, so the scheduler's
            # view of the victim never goes stale.
            lease = lease_of(server)
            doomed2 = next(m for m in members
                           if m.node != lease["holder"]
                           and m is not victim)
            metas[doomed2.index].set_data(
                tpu_vm(accelerator_type="v5litepod-16",
                       worker_id=doomed2.index, preemptible=True,
                       preempted=True))
            degraded = expected_labels(sid, hosts, hosts - 1)
            victim_want = {} if sink_mode == "file" else degraded
            want = {m.index: (degraded if m is not victim
                              else victim_want)
                    for m in members}
            soak.converge("asym-degrade", members, want,
                          budget_s=AGREEMENT_S + 6 * INTERVAL_S + 3)
            if sink_mode == "cr":
                hedger = next((m for m in survivors
                               if "slice-hedge" in m.journal_types()),
                              None)
                require(hedger is not None,
                        "cr sink: no member journaled slice-hedge for "
                        "the severed member's publish")
                hedged_now = hedger.metric(
                    "tfd_slice_hedged_publishes_total")
                require(hedged_now > 0,
                        "slice-hedge journaled but the hedged-publishes "
                        "counter never moved")
                soak.note_counter("slice_hedged_publishes", hedged_now)
            metas[doomed2.index].set_data(
                tpu_vm(accelerator_type="v5litepod-16",
                       worker_id=doomed2.index, preemptible=True))
            healthy = expected_labels(sid, hosts, hosts)
            victim_want = {} if sink_mode == "file" else healthy
            want = {m.index: (healthy if m is not victim
                              else victim_want)
                    for m in members}
            soak.converge("asym-recover", members, want,
                          budget_s=AGREEMENT_S + 6 * INTERVAL_S + 3,
                          enforce_window=False)

            # 4d. Heal the asymmetric partition: the relay kept the
            # victim CONTINUOUSLY present in the leader's merge, so
            # unlike a full partition there is no rejoin dwell — the
            # victim re-owns its publish as soon as its blackboard
            # contact returns.
            listeners[victim.index].start()
            soak.converge("asym-heal", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=LEASE_S + 10, enforce_window=False)
            if sink_mode == "cr":
                mgrs = server.field_managers(
                    NS, f"tfd-features-for-{victim.node}")
                require(not mgrs.get("tfd-hedge"),
                        f"healed member never reclaimed its hedged "
                        f"slice labels (tfd-hedge still owns "
                        f"{sorted(mgrs.get('tfd-hedge', ()))})")
            soak.watch_steady(members, 2, phase="w9b")
            soak.settle("pre-brownout", members,
                        expected_labels(sid, hosts, hosts),
                        quiet_s=2, budget_s=LEASE_S + 10)

            # 4e. Leader loss MID-BROWNOUT: cap the apiserver below the
            # fleet's offered load (4 hosts x ~2 requests/s against a
            # 7/s bucket guarantees 429s every second while all four
            # live), then SIGKILL the holder. The verdict already
            # names the line of succession, so the first listed live
            # successor takes the lease at its first MISSED-RENEWAL
            # tick — ahead of full lease expiry — and the survivors
            # converge while still throttled (paced retries stagger
            # publishes, so the disagreement window is measured, not
            # enforced).
            server.set_capacity(7)
            time.sleep(2)  # let the throttle actually bite
            lease = lease_of(server)
            leader = next(m for m in members if m.node == lease["holder"])
            survivors = [m for m in members if m is not leader]
            succ_before = {
                m.index: m.metric("tfd_slice_successions_total")
                for m in survivors}
            epoch_before = lease_of(server)["epoch"]
            leader.kill(signal.SIGKILL)
            soak.converge(
                "brownout-succession", members,
                expected_labels(sid, hosts, hosts - 1),
                budget_s=LEASE_S + AGREEMENT_S + 4 * INTERVAL_S + 5,
                extra_check=lambda: (lease_of(server) or {}).get(
                    "epoch", 0) > epoch_before,
                enforce_window=False)
            new_holder = next(m for m in members
                              if m.node == lease_of(server)["holder"])
            require("slice-succession" in new_holder.journal_types(),
                    "new holder never journaled slice-succession (the "
                    "lease moved by expiry, not succession)")
            succ_now = new_holder.metric("tfd_slice_successions_total")
            require(succ_now > succ_before[new_holder.index],
                    "slice-succession journaled but the successions "
                    "counter never moved for the new holder")
            soak.note_counter("slice_successions", succ_now)
            server.set_capacity(0)
            leader.start()
            soak.converge("brownout-clear", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=20, enforce_window=False)
            soak.watch_steady(members, 2, phase="w9c")
            soak.settle("pre-kill9", members,
                        expected_labels(sid, hosts, hosts),
                        quiet_s=2, budget_s=LEASE_S + 10)

            # 5. kill -9 the leader and restart it IMMEDIATELY with the
            # same state file: the lease must be resumed (no epoch
            # bump) and the survivors' labels must never move.
            lease = lease_of(server)
            leader = next(m for m in members if m.node == lease["holder"])
            survivors = [m for m in members if m is not leader]
            before = {m.index: m.slice_labels() for m in survivors}
            epoch_before = lease_of(server)["epoch"]
            leader.kill(signal.SIGKILL)
            leader.start()

            def lease_resumed():
                lease_now = lease_of(server)
                return (lease_now and lease_now["holder"] == leader.node
                        and lease_now["renewed_at"] > lease["renewed_at"])
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not lease_resumed():
                sets = {m.index: m.slice_labels() for m in survivors}
                require(sets == before,
                        f"survivor labels moved during the leader's "
                        f"kill -9 restart: {sets} != {before}")
                time.sleep(0.1)
            require(lease_resumed(), "kill -9'd leader did not resume")
            require(lease_of(server)["epoch"] == epoch_before,
                    "lease epoch bumped across kill -9 (leadership "
                    "flapped instead of resuming from the state file)")
            require("slice-restored" in leader.journal_types(),
                    "restarted leader never journaled slice-restored")
            soak.steps.append({"name": "kill9-leader-resume",
                               "latency_ms": 0.0, "disagreement_ms": 0.0})
            soak.watch_steady(members, 3, phase="w10")

            require(soak.interleaved == 0,
                    f"{soak.interleaved} steady-state sample(s) showed "
                    f"two live hosts publishing disagreeing slice labels")
            record = soak.record()
            record["sink"] = sink_mode
            record["orphan_self_demoted"] = True
            record["leader_failover_epoch_bump"] = True
            record["kill9_lease_resumed"] = True
            record["asym_peers_never_degraded"] = True
            record["succession_under_brownout"] = True
            record["slice_relayed_reports"] = max(
                sum(m.metric("tfd_slice_relayed_reports_total")
                    for m in members),
                soak.counter_floors.get("slice_relayed_reports", 0))
            record["slice_successions"] = max(
                sum(m.metric("tfd_slice_successions_total")
                    for m in members),
                soak.counter_floors.get("slice_successions", 0))
            record["slice_hedged_publishes"] = max(
                sum(m.metric("tfd_slice_hedged_publishes_total")
                    for m in members),
                soak.counter_floors.get("slice_hedged_publishes", 0))
            return record
        finally:
            for m in members:
                if m.proc is not None:
                    m.kill(signal.SIGTERM)
            for listener in listeners:
                listener.stop()
            for meta in metas:
                meta.__exit__(None, None, None)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=4)
    ap.add_argument("--seed", type=int, default=10)
    ap.add_argument("--json", metavar="FILE",
                    help="write the bench record here")
    ap.add_argument("--sink", choices=("file", "cr"), default="file",
                    help="label sink the members publish through: the "
                         "label file (default) or the NodeFeature-CR "
                         "watch+SSA path (coherence then sampled from "
                         "the fake apiserver's CR store)")
    ap.add_argument("--workdir", metavar="DIR",
                    help="run in DIR and keep it (per-member daemon "
                         "logs survive a failed drill for post-mortem); "
                         "default is a throwaway temp dir")
    args = ap.parse_args(argv)

    if not BINARY.exists() or not FAKE_PJRT.exists():
        print("build/ artifacts missing; run the build first (the "
              "pytest conftest or cmake+ninja)", file=sys.stderr)
        return 2

    import contextlib
    import tempfile
    if args.workdir:
        workdir = Path(args.workdir)
        workdir.mkdir(parents=True, exist_ok=True)
        ctx = contextlib.nullcontext(str(workdir))
    else:
        ctx = tempfile.TemporaryDirectory(prefix="slice-soak-")
    with ctx as tmp:
        try:
            record = run_soak(args.hosts, args.seed, Path(tmp),
                              sink_mode=args.sink)
        except SoakError as e:
            print(f"slice soak FAILED: {e}", file=sys.stderr)
            return 1
    print(json.dumps(record, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f)
    print(f"slice soak OK: {len(record['steps'])} steps, agreement p50 "
          f"{record['slice_agreement_p50_ms']}ms, "
          f"{record['interleaved_disagreement_passes']} interleaved "
          f"disagreements over {record['coherence_samples']} samples")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Multi-host slice-coherence chaos soak (ISSUE 10 acceptance).

Boots N (default 4) REAL daemons as the member hosts of one fake slice —
every process loads the fake PJRT plugin with the SAME global topology
(TFD_FAKE_PJRT_BOUNDS) and its own host index (TFD_FAKE_PJRT_PROC), all
coordinating through ONE fake apiserver (each host on its own listener
so a single host can be network-partitioned) — then walks a seeded chaos
schedule:

  kill-follower     SIGKILL a non-leader member        -> survivors 3/4
  restart           bring it back                      -> 4/4
  crash-loop-dwell  kill/restart it twice inside the   -> healthy-hosts
                    rejoin dwell (--slice-rejoin-dwell)   NEVER flaps up
                                                          per restart;
                                                          re-counted only
                                                          after it stays
                                                          up through the
                                                          dwell
  kill-leader       SIGKILL the lease holder           -> failover + 3/4
  restart           bring it back                      -> 4/4
  wedge-pjrt        wedge one member's PJRT (hang file)-> 3/4 everywhere
  unwedge           lift the wedge                     -> 4/4
  preempt-notice    flip one member's GCE              -> the leader folds
                    instance/preempted to TRUE            the still-alive
                    (its own fake metadata server)        member into a
                                                          proactive 3/4
                                                          degraded verdict
  preempt-clear     notice cleared                     -> 4/4
  partition         refuse one member's apiserver      -> member drops
                                                          tpu.slice.*
                                                          (self-demotes),
                                                          peers 3/4
  heal              restore the listener               -> rejoin, 4/4
  kill9-leader      kill -9 the leader + instant       -> lease resumed
                    restart (same state file)             from the state
                                                          file: NO epoch
                                                          bump, survivors'
                                                          labels never
                                                          move

Invariants asserted at every step:
  - all live hosts' tpu.slice.* labels are BYTE-IDENTICAL once the step
    converges, and the disagreement window (first label movement ->
    convergence) is at most 2 probe intervals;
  - detection latency is bounded by the layer that owns it: the
    agreement timeout for a dead member, the PJRT fresh window for a
    wedged one, the lease duration for a partition;
  - ZERO "interleaved disagreement" samples: outside a step's
    convergence window, no sample may show two live hosts publishing
    different slice labels;
  - the partitioned member drops its slice labels entirely (never a
    stale slice view) and journals slice-orphaned;
  - the kill -9'd leader resumes its lease epoch from the state file.

`--json FILE` writes the bench record bench_gate.py --slice gates
against the committed BENCH_r10.json.

`--sink cr` runs the SAME schedule with every member publishing through
the NodeFeature-CR sink (watch + server-side apply against the fake
apiserver) instead of the label file — coherence is then sampled from
the CR store, the bytes a scheduler actually sees. Sole expected delta:
a partitioned member cannot write its self-demotion (the partition
severs the sink too), so the store holds its last-agreed labels until
heal; the demotion is still asserted via the slice-orphaned journal.

Usage:
  python3 scripts/slice_soak.py [--hosts 4] [--seed 10] [--json out.json]
      [--sink file|cr]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpufd import slicecoord  # noqa: E402
from tpufd.fakes import free_loopback_port  # noqa: E402
from tpufd.fakes.apiserver import FakeApiServer  # noqa: E402
from tpufd.fakes.metadata_server import FakeMetadataServer  # noqa: E402

BUILD = REPO / "build"
BINARY = BUILD / "tpu-feature-discovery"
FAKE_PJRT = BUILD / "libtfd_fake_pjrt.so"

INTERVAL_S = 1
AGREEMENT_S = 2
LEASE_S = 3
PJRT_TIMEOUT_S = 3
SLICE_ID = "soak-slice"
NS = "slice-soak"


class SoakError(AssertionError):
    pass


def require(cond, message):
    if not cond:
        raise SoakError(message)


class Member:
    def __init__(self, tmp, index, url, hosts, sink_mode="file",
                 cr_store=None, metadata_port=None):
        self.index = index
        self.node = f"soak-host-{index}"
        self.url = url
        self.sink_mode = sink_mode
        self.cr_store = cr_store  # the shared fake-apiserver store
        self.out_file = tmp / f"tfd-{index}"
        self.state_file = tmp / f"state-{index}"
        self.hang_file = tmp / f"hang-{index}"
        self.port = free_loopback_port()
        self.argv = [
            str(BINARY), f"--sleep-interval={INTERVAL_S}s",
            "--event-driven=false",  # cadence-shaped disagreement windows
            "--backend=pjrt", f"--libtpu-path={FAKE_PJRT}",
            f"--pjrt-init-timeout={PJRT_TIMEOUT_S}s",
            "--pjrt-refresh-interval=1s",
            # Failed inits are memoized; the default 60s window would
            # stretch un-wedge recovery far past the step budget.
            "--pjrt-retry-backoff=1s",
            "--machine-type-file=/dev/null",
            f"--output-file={self.out_file}",
            f"--state-file={self.state_file}",
            f"--introspection-addr=127.0.0.1:{self.port}",
            "--slice-coordination",
            f"--slice-lease-duration={LEASE_S}s",
            f"--slice-agreement-timeout={AGREEMENT_S}s",
            # A request in flight when a partition starts can hang to
            # the full deadline; keep it under the lease so ONE stalled
            # tick can't push self-demotion past the step budget.
            "--sink-request-deadline=2s",
            # Every boot's "waiting for the first device probe round"
            # slice-probe error costs 2 healthsm transitions that the
            # state file PERSISTS across restarts; the crash-loop-dwell
            # drill boots the same member 4 times (8 transitions),
            # which at the default threshold of 6 would quarantine its
            # slice source for the default 600s cooldown and wedge the
            # drill. 12 keeps the soak's restart budget under the bar
            # without masking anything the soak asserts (no step ever
            # legitimately quarantines here).
            "--health-flap-threshold=12",
            "--cadence-jitter-pct=0", "--no-timestamp",
            # Preemption fast path (ISSUE 13 satellite): every member
            # watches its own fake metadata server's instance/preempted.
            "--lifecycle-watch",
        ]
        if sink_mode == "cr":
            # The NodeFeature-CR sink variant (PR 9's nuance, closed
            # here): slice labels ride watch+SSA to the apiserver
            # instead of the label file; coherence is then sampled from
            # the CR store — the bytes a scheduler actually sees. The
            # breaker cooldown is shortened so the heal step re-asserts
            # at the protocol's cadence instead of parking the sink for
            # the default 30s after the partition's failed writes.
            self.argv += ["--use-node-feature-api", "--output-file=",
                          "--sink-breaker-cooldown=2s"]
        self.env = {
            **os.environ,
            "GCE_METADATA_HOST": (f"127.0.0.1:{metadata_port}"
                                  if metadata_port else "127.0.0.1:1"),
            "NODE_NAME": self.node,
            "TFD_APISERVER_URL": url,
            "KUBERNETES_NAMESPACE": NS,
            "TFD_SLICE_ID": SLICE_ID,
            "TFD_SLICE_WORKER_ID": str(index),
            "TFD_SLICE_HOSTS": "",  # set by the soak
            "TFD_FAKE_PJRT_KIND": "TPU v5 lite",
            "TFD_FAKE_PJRT_BOUNDS": "4,4,1",
            "TFD_FAKE_PJRT_HOSTS": "",  # set by the soak
            "TFD_FAKE_PJRT_PROC": str(index),
            "TFD_FAKE_PJRT_HANG_IF_FILE": str(self.hang_file),
        }
        self.proc = None

    def start(self):
        # Stderr kept per host (appended across restarts): the chaos
        # post-mortems need the coordinator's own account.
        self.log = open(self.out_file.parent / f"log-{self.index}", "a")
        self.proc = subprocess.Popen(self.argv, env=self.env,
                                     stderr=self.log)

    def kill(self, sig=signal.SIGKILL):
        if self.proc is None:
            return
        self.proc.send_signal(sig)
        self.proc.wait(timeout=10)
        self.proc = None

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def full_labels(self):
        if self.sink_mode == "cr":
            obj = self.cr_store.get((NS, f"tfd-features-for-{self.node}"))
            if obj is None:
                return None
            return dict((obj.get("spec") or {}).get("labels") or {})
        try:
            return dict(line.split("=", 1) for line in
                        self.out_file.read_text().splitlines() if line)
        except (OSError, ValueError):
            return None  # unreadable mid-write; sample again

    def slice_labels(self):
        labels = self.full_labels()
        if labels is None:
            return None
        return slicecoord.slice_labels_of(labels)

    def journal_types(self):
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/debug/journal?n=512",
                    timeout=2) as r:
                doc = json.loads(r.read().decode())
            return [e.get("type") for e in doc.get("events", [])]
        except Exception:
            return []


def expected_labels(sanitized_id, hosts, healthy):
    verdict = {"hosts": hosts, "healthy_hosts": healthy,
               "degraded": healthy < hosts, "class": "", "members": []}
    return slicecoord.build_slice_labels(sanitized_id, verdict)


class Soak:
    def __init__(self, hosts, seed):
        import random
        self.random = random.Random(seed)
        self.hosts = hosts
        self.sanitized_id = slicecoord.sanitize_slice_id(SLICE_ID)
        self.steps = []
        self.interleaved = 0
        self.samples = 0

    def sample_all(self, members):
        """One coherence sample across the live members; returns
        {index: labels} for members whose file is readable."""
        out = {}
        for m in members:
            if not m.alive():
                continue
            labels = m.slice_labels()
            if labels is not None:
                out[m.index] = labels
        return out

    def watch_steady(self, members, duration, phase=""):
        """Between steps: no sample may show two live hosts CLAIMING
        different slice facts — the 'interleaved disagreement' the
        acceptance forbids. A host with NO slice labels is abstaining
        (the designed self-demoted/booting state: agreed-or-absent),
        not disagreeing."""
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            sets = [s for s in self.sample_all(members).values() if s]
            self.samples += 1
            if sets and any(s != sets[0] for s in sets[1:]):
                self.interleaved += 1
                if phase:
                    print(f"    DISAGREE[{phase}]: {sets}")
            time.sleep(0.1)

    def converge(self, name, members, want, budget_s, extra_check=None,
                 enforce_window=True):
        """Waits for every live member's slice labels to equal `want`
        (a dict, or a per-index dict-of-dicts), measuring convergence
        latency and the disagreement window. `budget_s` bounds the
        whole step. `enforce_window` applies the 2-probe-interval
        disagreement bound — the FAILURE-relabeling acceptance; steps
        that include a host booting (join, rejoins) measure but don't
        enforce it, since a cold daemon's settle window is not a
        coherence failure."""
        t0 = time.monotonic()
        first_change = None
        baseline = self.sample_all(members)
        while True:
            now = time.monotonic()
            sample = self.sample_all(members)
            self.samples += 1
            if first_change is None and sample != baseline:
                first_change = now
            def want_of(index):
                if want and isinstance(next(iter(want.values()), None),
                                       dict):
                    return want.get(index, {})
                return want
            done = all(m.alive() is False or
                       sample.get(m.index) == want_of(m.index)
                       for m in members)
            if done and (extra_check is None or extra_check()):
                break
            require(now - t0 < budget_s,
                    f"step {name}: no convergence within {budget_s}s "
                    f"(sample {sample}; extra_check="
                    f"{extra_check() if extra_check else None})")
            time.sleep(0.05)
        t_converged = time.monotonic()
        latency_ms = (t_converged - t0) * 1000
        disagreement_ms = ((t_converged - first_change) * 1000
                           if first_change is not None else 0.0)
        if enforce_window:
            require(disagreement_ms <= 2 * INTERVAL_S * 1000 + 500,
                    f"step {name}: disagreement window "
                    f"{disagreement_ms:.0f}ms exceeds 2 probe intervals")
        self.steps.append({"name": name,
                           "latency_ms": round(latency_ms, 1),
                           "disagreement_ms": round(disagreement_ms, 1)})
        print(f"  step {name}: converged in {latency_ms:.0f}ms "
              f"(disagreement window {disagreement_ms:.0f}ms)")

    def record(self):
        latencies = sorted(s["latency_ms"] for s in self.steps)
        p50 = latencies[len(latencies) // 2] if latencies else None
        return {
            "soak": "slice",
            "hosts": self.hosts,
            "interval_s": INTERVAL_S,
            "agreement_timeout_s": AGREEMENT_S,
            "lease_duration_s": LEASE_S,
            "steps": self.steps,
            "slice_agreement_p50_ms": p50,
            "max_disagreement_ms": max(
                (s["disagreement_ms"] for s in self.steps), default=0),
            "interleaved_disagreement_passes": self.interleaved,
            "coherence_samples": self.samples,
        }


def lease_of(server):
    doc = server.store.get(
        (NS, "tfd-slice-" + slicecoord.sanitize_slice_id(SLICE_ID)))
    raw = (doc or {}).get("data", {}).get("lease")
    return json.loads(raw) if raw else None


def run_soak(hosts, seed, tmp, sink_mode="file"):
    soak = Soak(hosts, seed)
    sid = soak.sanitized_id
    # One fake metadata server per member so the preemption drill can
    # flip ONE host's instance/preempted without touching the others.
    from tpufd.fakes.metadata_server import tpu_vm
    metas = [FakeMetadataServer(tpu_vm(accelerator_type="v5litepod-16",
                                       worker_id=i, preemptible=True))
             for i in range(hosts)]
    for meta in metas:
        meta.__enter__()
    with FakeApiServer() as server:
        listeners = [server.add_listener() for _ in range(hosts)]
        members = [Member(tmp, i, listeners[i].url, hosts,
                          sink_mode=sink_mode, cr_store=server.store,
                          metadata_port=metas[i].port)
                   for i in range(hosts)]
        for m in members:
            m.env["TFD_SLICE_HOSTS"] = str(hosts)
            m.env["TFD_FAKE_PJRT_HOSTS"] = str(hosts)
        try:
            print(f"slice soak: {hosts} hosts, seed {seed}, "
                  f"sink={sink_mode}")
            for m in members:
                m.start()
            # Join: everyone healthy, byte-identical. Cold PJRT probes
            # pay the settle window once.
            soak.converge("join", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=30, enforce_window=False)
            soak.watch_steady(members, 2, phase="w1")

            lease = lease_of(server)
            require(lease is not None, "no lease on the blackboard")
            leader = next(m for m in members if m.node == lease["holder"])
            follower = next(m for m in members if m is not leader)

            # 1. Kill a follower: detection <= agreement timeout, then
            # a 2-interval convergence window.
            follower.kill(signal.SIGKILL)
            soak.converge("kill-follower", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=AGREEMENT_S + 4 * INTERVAL_S + 3)
            soak.watch_steady(members, 2, phase="w2")
            follower.start()
            soak.converge("member-rejoin", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=20, enforce_window=False)
            soak.watch_steady(members, 2, phase="w3")

            # 1b. Crash-loop rejoin hysteresis (ISSUE 11 satellite,
            # --slice-rejoin-dwell at its auto default = 2x the
            # agreement timeout): a member restarting FASTER than the
            # dwell must not flap healthy-hosts back up once per
            # restart — the leader re-counts it only after it stays
            # continuously present through the dwell.
            follower.kill(signal.SIGKILL)
            soak.converge("dwell-depart", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=AGREEMENT_S + 4 * INTERVAL_S + 3)
            follower.start()
            # While the crash-looper is inside its dwell, no SURVIVOR
            # may claim full health — this is the flap the hysteresis
            # exists to prevent. (The restarting member itself is
            # excluded: its on-disk label file legitimately holds the
            # pre-kill bytes until its first warm-restart pass.)
            survivors = [m for m in members if m is not follower]
            flap_deadline = time.monotonic() + 3 * INTERVAL_S
            while time.monotonic() < flap_deadline:
                for index, labels in soak.sample_all(survivors).items():
                    if labels:
                        require(labels[slicecoord.SLICE_HEALTHY_HOSTS]
                                != str(hosts),
                                f"crash-looper re-counted healthy inside "
                                f"its rejoin dwell (healthy-hosts "
                                f"flapped; host {index} published "
                                f"{labels})")
                soak.samples += 1
                time.sleep(0.1)
            # Second crash inside the dwell, then a real recovery: the
            # departure clock refreshes, so full health returns only
            # after the member finally stays up through the dwell.
            follower.kill(signal.SIGKILL)
            follower.start()
            soak.converge("crash-loop-dwell", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=2 * AGREEMENT_S + 25,
                          enforce_window=False)
            lease = lease_of(server)
            dwell_leader = next(m for m in members
                                if m.node == lease["holder"])
            require("slice-rejoin-dwell" in dwell_leader.journal_types(),
                    "leader never journaled slice-rejoin-dwell for the "
                    "crash-looping member")
            soak.watch_steady(members, 2, phase="w3b")

            # 2. Kill the leader: lease failover (epoch bump) + the
            # same coherent degrade on every survivor. Re-resolve the
            # holder first — leadership may have moved since the join.
            lease = lease_of(server)
            leader = next(m for m in members if m.node == lease["holder"])
            epoch_before = lease["epoch"]
            leader.kill(signal.SIGKILL)
            soak.converge(
                "kill-leader", members,
                expected_labels(sid, hosts, hosts - 1),
                budget_s=LEASE_S + AGREEMENT_S + 4 * INTERVAL_S + 3,
                extra_check=lambda: (lease_of(server) or {}).get(
                    "epoch", 0) > epoch_before)
            require(lease_of(server)["holder"] != leader.node,
                    "dead leader still holds the lease")
            soak.watch_steady(members, 2, phase="w4")
            leader.start()
            soak.converge("leader-rejoin", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=20, enforce_window=False)
            soak.watch_steady(members, 2, phase="w5")

            # 3. Wedge one member's PJRT: its device snapshot ages out
            # of fresh, its report turns unhealthy, the SLICE degrades
            # — coherently, on all four LIVE hosts (the wedged one
            # publishes the same agreed verdict).
            wedged = next(m for m in members
                          if m.node != lease_of(server)["holder"])
            wedged.hang_file.touch()
            fresh_window = 4 * INTERVAL_S + PJRT_TIMEOUT_S
            soak.converge("wedge-pjrt", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=fresh_window + AGREEMENT_S +
                          4 * INTERVAL_S + 5)
            soak.watch_steady(members, 2, phase="w6")
            wedged.hang_file.unlink()
            soak.converge("unwedge", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=fresh_window + 10,
                          enforce_window=False)
            soak.watch_steady(members, 2, phase="w7")

            # 3b. Preemption fast path (ISSUE 13 satellite): GCE issues
            # a preemption notice to one member. Its lifecycle source
            # (1s tick here) publishes tpu.lifecycle.preempt-imminent,
            # the report carries preempting=true, and the LEADER folds
            # the still-alive-but-doomed member into a proactive
            # degraded verdict — every host relabels 3/4 coherently
            # BEFORE the VM actually dies.
            lease = lease_of(server)
            doomed = next(m for m in members
                          if m.node != lease["holder"])
            notice = tpu_vm(accelerator_type="v5litepod-16",
                            worker_id=doomed.index, preemptible=True,
                            preempted=True)
            metas[doomed.index].set_data(notice)
            soak.converge("preempt-notice", members,
                          expected_labels(sid, hosts, hosts - 1),
                          budget_s=AGREEMENT_S + 6 * INTERVAL_S + 3)
            require("lifecycle-change" in doomed.journal_types(),
                    "preempted member never journaled lifecycle-change")
            doomed_labels = doomed.full_labels() or {}
            require(doomed_labels.get(
                        "google.com/tpu.lifecycle.preempt-imminent")
                    == "true",
                    f"preempted member never published preempt-imminent "
                    f"(labels {doomed_labels})")
            soak.watch_steady(members, 2, phase="w7b")
            # The notice clears (drill ends; in production the VM dies
            # and the kill/restart steps above cover that path).
            metas[doomed.index].set_data(
                tpu_vm(accelerator_type="v5litepod-16",
                       worker_id=doomed.index, preemptible=True))
            soak.converge("preempt-clear", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=AGREEMENT_S + 6 * INTERVAL_S + 3,
                          enforce_window=False)
            soak.watch_steady(members, 2, phase="w7c")

            # 4. Partition one member from the apiserver: it must
            # SELF-DEMOTE (drop tpu.slice.* entirely — never a stale
            # slice view) while the peers degrade the slice.
            lease = lease_of(server)
            victim = next(m for m in members
                          if m.node != lease["holder"])
            listeners[victim.index].stop()
            # File sink: the victim's self-demotion (drop tpu.slice.*)
            # is visible in its label file. CR sink: the victim CANNOT
            # write its demotion — the partition severs the sink too —
            # so the store legitimately holds its LAST-AGREED labels
            # until heal (the documented partition tradeoff); the
            # demotion itself is still asserted via the slice-orphaned
            # journal below, read over local introspection.
            victim_want = ({} if sink_mode == "file"
                           else expected_labels(sid, hosts, hosts))
            want = {m.index: (expected_labels(sid, hosts, hosts - 1)
                              if m is not victim else victim_want)
                    for m in members}
            soak.converge("partition", members, want,
                          budget_s=LEASE_S + AGREEMENT_S +
                          4 * INTERVAL_S + 3)
            require("slice-orphaned" in victim.journal_types(),
                    "partitioned member never journaled slice-orphaned")
            soak.watch_steady([m for m in members if m is not victim], 2, phase="w8")
            listeners[victim.index].start()
            soak.converge("heal", members,
                          expected_labels(sid, hosts, hosts),
                          budget_s=LEASE_S + 15, enforce_window=False)
            soak.watch_steady(members, 2, phase="w9")

            # 5. kill -9 the leader and restart it IMMEDIATELY with the
            # same state file: the lease must be resumed (no epoch
            # bump) and the survivors' labels must never move.
            lease = lease_of(server)
            leader = next(m for m in members if m.node == lease["holder"])
            survivors = [m for m in members if m is not leader]
            before = {m.index: m.slice_labels() for m in survivors}
            epoch_before = lease_of(server)["epoch"]
            leader.kill(signal.SIGKILL)
            leader.start()

            def lease_resumed():
                lease_now = lease_of(server)
                return (lease_now and lease_now["holder"] == leader.node
                        and lease_now["renewed_at"] > lease["renewed_at"])
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not lease_resumed():
                sets = {m.index: m.slice_labels() for m in survivors}
                require(sets == before,
                        f"survivor labels moved during the leader's "
                        f"kill -9 restart: {sets} != {before}")
                time.sleep(0.1)
            require(lease_resumed(), "kill -9'd leader did not resume")
            require(lease_of(server)["epoch"] == epoch_before,
                    "lease epoch bumped across kill -9 (leadership "
                    "flapped instead of resuming from the state file)")
            require("slice-restored" in leader.journal_types(),
                    "restarted leader never journaled slice-restored")
            soak.steps.append({"name": "kill9-leader-resume",
                               "latency_ms": 0.0, "disagreement_ms": 0.0})
            soak.watch_steady(members, 3, phase="w10")

            require(soak.interleaved == 0,
                    f"{soak.interleaved} steady-state sample(s) showed "
                    f"two live hosts publishing disagreeing slice labels")
            record = soak.record()
            record["sink"] = sink_mode
            record["orphan_self_demoted"] = True
            record["leader_failover_epoch_bump"] = True
            record["kill9_lease_resumed"] = True
            return record
        finally:
            for m in members:
                if m.proc is not None:
                    m.kill(signal.SIGTERM)
            for listener in listeners:
                listener.stop()
            for meta in metas:
                meta.__exit__(None, None, None)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=4)
    ap.add_argument("--seed", type=int, default=10)
    ap.add_argument("--json", metavar="FILE",
                    help="write the bench record here")
    ap.add_argument("--sink", choices=("file", "cr"), default="file",
                    help="label sink the members publish through: the "
                         "label file (default) or the NodeFeature-CR "
                         "watch+SSA path (coherence then sampled from "
                         "the fake apiserver's CR store)")
    args = ap.parse_args(argv)

    if not BINARY.exists() or not FAKE_PJRT.exists():
        print("build/ artifacts missing; run the build first (the "
              "pytest conftest or cmake+ninja)", file=sys.stderr)
        return 2

    import tempfile
    with tempfile.TemporaryDirectory(prefix="slice-soak-") as tmp:
        try:
            record = run_soak(args.hosts, args.seed, Path(tmp),
                              sink_mode=args.sink)
        except SoakError as e:
            print(f"slice soak FAILED: {e}", file=sys.stderr)
            return 1
    print(json.dumps(record, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f)
    print(f"slice soak OK: {len(record['steps'])} steps, agreement p50 "
          f"{record['slice_agreement_p50_ms']}ms, "
          f"{record['interleaved_disagreement_passes']} interleaved "
          f"disagreements over {record['coherence_samples']} samples")
    return 0


if __name__ == "__main__":
    sys.exit(main())

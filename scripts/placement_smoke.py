#!/usr/bin/env python3
"""Placement-service smoke against the REAL binary (ISSUE 17).

Boots `tpu-feature-discovery --mode=placement` against a
tpufd.fakes.apiserver seeded with a scaled fleet, churns the label
surface well past the fake's DEFAULT watch-history window, and asserts:

  - /readyz gates on informer sync, then answers track a
    tpufd.placement twin fed the identical label stream — exact
    equality on every (class, chips, slice, limit) probe;
  - queries are served from the in-memory index: ZERO apiserver reads
    land while the query battery runs;
  - churn never degenerates into a 410 relist storm: the apiserver's
    history depth is sized PROPORTIONALLY to the fleet
    (collection_history = max(256, 2 * nodes) — the same rule of thumb
    docs/placement-harness.md states for real deployments), so a watch
    reconnect during the churn burst can always resume above the
    compaction floor. The smoke counts collection LISTs: one initial
    sync, none forced by churn;
  - the admission gate composes in: zeroed capacity labels on the
    inventory object flip a gold query to no-capacity, deleting the
    object admits it again.

This is the CI-shaped end of the ISSUE 17 scale story: the 100k-node
numbers live in scripts/cluster_soak.py --placement-qps (virtual clock,
twin stores); THIS proves the real binary speaks the same contract on a
real socket.

Usage:
  python3 scripts/placement_smoke.py [--binary build/tpu-feature-discovery]
      [--nodes 600] [--churn 400] [--seed 17]
"""

import argparse
import http.client
import json
import os
import random
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from tpufd import agg as agglib  # noqa: E402
from tpufd import metrics as metricslib  # noqa: E402
from tpufd import placement as placementlib  # noqa: E402
from tpufd.fakes.apiserver import FakeApiServer  # noqa: E402

NS = "placement-smoke"
NODE_NAME_LABEL = "nfd.node.kubernetes.io/node-name"
OUTPUT = "tfd-cluster-inventory"

PROBES = [
    {"class": "any", "chips": 1},
    {"class": "any", "chips": 8, "limit": 8},
    {"class": "gold", "chips": 4},
    {"class": "gold", "chips": 8, "slice": True, "limit": 4},
    {"class": "silver", "chips": 4, "slice": True},
    {"class": "silver", "chips": 16},
    {"class": "any", "chips": 4, "slice": True, "limit": 16},
]


def free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http_get(port, path, timeout=5):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    except OSError:
        return None, ""
    finally:
        conn.close()


def wait_for(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return cond()


def post_placement(port, doc):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("POST", "/v1/placements", body=json.dumps(doc),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def node_labels(rng, i):
    labels = {
        agglib.TPU_COUNT: str([4, 8, 16][i % 3]),
        agglib.PERF_CLASS: ["gold", "silver", "degraded", ""][i % 4],
        agglib.SLICE_ID: f"sm-{i // 8}",
        agglib.SLICE_DEGRADED: "true" if i % 41 == 0 else "false",
    }
    if i % 29 == 0:
        labels[agglib.LIFECYCLE_PREEMPT] = "true"
    return labels


def churn_labels(rng, old):
    new = dict(old)
    roll = rng.random()
    if roll < 0.4:
        new[agglib.PERF_CLASS] = rng.choice(["gold", "silver", "degraded"])
    elif roll < 0.65:
        new[agglib.SLICE_DEGRADED] = \
            "false" if old.get(agglib.SLICE_DEGRADED) == "true" else "true"
    elif roll < 0.8:
        if agglib.LIFECYCLE_PREEMPT in new:
            del new[agglib.LIFECYCLE_PREEMPT]
        else:
            new[agglib.LIFECYCLE_PREEMPT] = "true"
    else:
        new[agglib.TPU_COUNT] = rng.choice(["4", "8", "16"])
    return new


def collection_lists(server):
    """LIST requests on the bare collection (the relist signature) —
    watches are logged with the WATCH method marker and don't count."""
    return sum(1 for method, path in server.requests
               if method == "GET" and path.rstrip("/").endswith(
                   "/nodefeatures"))


def probe_battery(port, twin, problems, tag):
    for probe in PROBES:
        want = twin.query(wanted=probe["class"],
                          chips=probe.get("chips", 1),
                          slice=probe.get("slice", False),
                          limit=probe.get("limit", 1))
        status, got = post_placement(port, probe)
        if status != 200:
            problems.append(f"{tag}: probe {probe} -> HTTP {status}")
        elif got != want:
            problems.append(
                f"{tag}: probe {probe} diverged from the twin: "
                f"service {got} vs twin {want}")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--binary", default="build/tpu-feature-discovery")
    ap.add_argument("--nodes", type=int, default=600)
    ap.add_argument("--churn", type=int, default=400,
                    help="label mutations to stream (sized past the "
                         "fake apiserver's DEFAULT 64-event window)")
    ap.add_argument("--seed", type=int, default=17)
    args = ap.parse_args(argv)

    rng = random.Random(args.seed)
    problems = []
    # The satellite rule of thumb under test: history depth scales with
    # the fleet, so churn bursts proportional to fleet size can never
    # push the compaction floor past a live consumer's resume point.
    depth = max(256, 2 * args.nodes)

    with FakeApiServer(collection_history=depth) as server:
        twin = placementlib.PlacementIndex()
        fleet = {}
        for i in range(args.nodes):
            node = f"sp-{i:05d}"
            labels = node_labels(rng, i)
            fleet[node] = labels
            server.seed(NS, f"tfd-features-for-{node}", labels,
                        {NODE_NAME_LABEL: node})
            twin.apply_node(node, labels)

        qport, oport = free_port(), free_port()
        proc = subprocess.Popen(
            [args.binary, "--mode=placement",
             f"--placement-listen-addr=127.0.0.1:{qport}",
             f"--introspection-addr=127.0.0.1:{oport}"],
            env={**os.environ, "TFD_APISERVER_URL": server.url,
                 "KUBERNETES_NAMESPACE": NS,
                 "POD_NAME": "placement-smoke-0",
                 "GCE_METADATA_HOST": "127.0.0.1:1"},
            stderr=subprocess.DEVNULL)
        try:
            if not wait_for(
                    lambda: http_get(qport, "/readyz")[0] == 200):
                print("placement smoke FAILED: /readyz never went 200",
                      file=sys.stderr)
                return 1
            lists_after_sync = collection_lists(server)

            probe_battery(qport, twin, problems, "post-sync")
            reads_before = len(server.requests)
            probe_battery(qport, twin, problems, "read-free")
            if len(server.requests) != reads_before:
                problems.append(
                    f"{len(server.requests) - reads_before} apiserver "
                    "request(s) landed DURING the query battery — "
                    "queries must be served from the in-memory index")

            # Churn far past the default 64-event history window.
            nodes = sorted(fleet)
            for _ in range(args.churn):
                node = rng.choice(nodes)
                fleet[node] = churn_labels(rng, fleet[node])
                server.seed(NS, f"tfd-features-for-{node}", fleet[node],
                            {NODE_NAME_LABEL: node})
                twin.apply_node(node, fleet[node])

            # Convergence: the service's event counter catches up, then
            # the battery must agree again.
            def caught_up():
                status, body = http_get(oport, "/metrics")
                if status != 200:
                    return False
                try:
                    n = metricslib.sample_value(
                        body, "tfd_placement_nodes", None)
                except ValueError:
                    return False
                if n != float(args.nodes):
                    return False
                for probe in PROBES[:2]:
                    want = twin.query(wanted=probe["class"],
                                      chips=probe.get("chips", 1),
                                      slice=probe.get("slice", False),
                                      limit=probe.get("limit", 1))
                    _, got = post_placement(qport, probe)
                    if got != want:
                        return False
                return True

            if not wait_for(caught_up):
                problems.append(
                    "service never converged with the twin after "
                    f"{args.churn} churn events")
            probe_battery(qport, twin, problems, "post-churn")

            relists = collection_lists(server) - lists_after_sync
            if relists != 0:
                problems.append(
                    f"{relists} collection relist(s) during churn — a "
                    "410 storm the proportional history depth "
                    f"({depth} events for {args.nodes} nodes) is there "
                    "to prevent")

            # Admission gate end to end: zeroed capacity refuses gold,
            # deleting the inventory object admits again.
            zeroed = {agglib.CAPACITY_PREFIX + "gold": "0",
                      agglib.CAPACITY_PREFIX + "silver": "0",
                      agglib.CAPACITY_PREFIX + "unclassed": "0"}
            server.seed(NS, OUTPUT, zeroed)
            twin.apply_inventory(zeroed)
            gold = {"class": "gold", "chips": 4}
            if not wait_for(lambda: post_placement(qport, gold)[1] ==
                            twin.query(wanted="gold", chips=4)):
                problems.append("zeroed inventory never flipped the "
                                "gold query to no-capacity")
            server.delete(NS, OUTPUT)
            twin.apply_inventory({})
            if not wait_for(lambda: post_placement(qport, gold)[1] ==
                            twin.query(wanted="gold", chips=4)):
                problems.append("deleting the inventory object never "
                                "re-admitted the gold query")
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)

    summary = {
        "nodes": args.nodes,
        "churn_events": args.churn,
        "collection_history": depth,
        "probes": len(PROBES) * 3 + 2,
        "problems": problems,
    }
    print(json.dumps(summary))
    if problems:
        for p in problems:
            print(f"placement smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(f"placement smoke OK: {args.nodes} nodes, {args.churn} churn "
          f"events through a {depth}-deep history with zero relists, "
          "service == twin on every probe")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Placement-service smoke against the REAL binary (ISSUE 17).

Boots `tpu-feature-discovery --mode=placement` against a
tpufd.fakes.apiserver seeded with a scaled fleet, churns the label
surface well past the fake's DEFAULT watch-history window, and asserts:

  - /readyz gates on informer sync, then answers track a
    tpufd.placement twin fed the identical label stream — exact
    equality on every (class, chips, slice, limit) probe;
  - queries are served from the in-memory index: ZERO apiserver reads
    land while the query battery runs;
  - churn never degenerates into a 410 relist storm: the apiserver's
    history depth is sized PROPORTIONALLY to the fleet
    (collection_history = max(256, 2 * nodes) — the same rule of thumb
    docs/placement-harness.md states for real deployments), so a watch
    reconnect during the churn burst can always resume above the
    compaction floor. The smoke counts collection LISTs: one initial
    sync, none forced by churn;
  - the admission gate composes in: zeroed capacity labels on the
    inventory object flip a gold query to no-capacity, deleting the
    object admits it again.

This is the CI-shaped end of the ISSUE 17 scale story: the 100k-node
numbers live in scripts/cluster_soak.py --placement-qps (virtual clock,
twin stores); THIS proves the real binary speaks the same contract on a
real socket.

With --explain (ISSUE 18) it runs the explainability drill instead: a
small crafted fleet hitting every rejection-taxonomy reason, seeded
WITH tfd.google.com/change-id annotations, and asserts that explained
answers match the tpufd.placement twin exactly (reasons, blocking
member, pinned counterfactual strings, change-id joins), that the
explained battery still lands ZERO apiserver reads, that a non-explain
answer's bytes are a byte-prefix of the explained one (the explain
section strictly appends — pay-for-what-you-use), and that
GET /v1/decisions replays the audit ring (job/node filters, n bound,
an `evicted` entry carrying the change-id after a node CR deletion).

Usage:
  python3 scripts/placement_smoke.py [--binary build/tpu-feature-discovery]
      [--nodes 600] [--churn 400] [--seed 17] [--explain]
"""

import argparse
import http.client
import json
import os
import random
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from tpufd import agg as agglib  # noqa: E402
from tpufd import metrics as metricslib  # noqa: E402
from tpufd import placement as placementlib  # noqa: E402
from tpufd.fakes.apiserver import FakeApiServer  # noqa: E402

NS = "placement-smoke"
NODE_NAME_LABEL = "nfd.node.kubernetes.io/node-name"
OUTPUT = "tfd-cluster-inventory"

PROBES = [
    {"class": "any", "chips": 1},
    {"class": "any", "chips": 8, "limit": 8},
    {"class": "gold", "chips": 4},
    {"class": "gold", "chips": 8, "slice": True, "limit": 4},
    {"class": "silver", "chips": 4, "slice": True},
    {"class": "silver", "chips": 16},
    {"class": "any", "chips": 4, "slice": True, "limit": 16},
]


def free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http_get(port, path, timeout=5):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    except OSError:
        return None, ""
    finally:
        conn.close()


def wait_for(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return cond()


def post_placement_raw(port, doc):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("POST", "/v1/placements", body=json.dumps(doc),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def post_placement(port, doc):
    status, body = post_placement_raw(port, doc)
    return status, json.loads(body)


def node_labels(rng, i):
    labels = {
        agglib.TPU_COUNT: str([4, 8, 16][i % 3]),
        agglib.PERF_CLASS: ["gold", "silver", "degraded", ""][i % 4],
        agglib.SLICE_ID: f"sm-{i // 8}",
        agglib.SLICE_DEGRADED: "true" if i % 41 == 0 else "false",
    }
    if i % 29 == 0:
        labels[agglib.LIFECYCLE_PREEMPT] = "true"
    return labels


def churn_labels(rng, old):
    new = dict(old)
    roll = rng.random()
    if roll < 0.4:
        new[agglib.PERF_CLASS] = rng.choice(["gold", "silver", "degraded"])
    elif roll < 0.65:
        new[agglib.SLICE_DEGRADED] = \
            "false" if old.get(agglib.SLICE_DEGRADED) == "true" else "true"
    elif roll < 0.8:
        if agglib.LIFECYCLE_PREEMPT in new:
            del new[agglib.LIFECYCLE_PREEMPT]
        else:
            new[agglib.LIFECYCLE_PREEMPT] = "true"
    else:
        new[agglib.TPU_COUNT] = rng.choice(["4", "8", "16"])
    return new


def collection_lists(server):
    """LIST requests on the bare collection (the relist signature) —
    watches are logged with the WATCH method marker and don't count."""
    return sum(1 for method, path in server.requests
               if method == "GET" and path.rstrip("/").endswith(
                   "/nodefeatures"))


def probe_battery(port, twin, problems, tag):
    for probe in PROBES:
        want = twin.query(wanted=probe["class"],
                          chips=probe.get("chips", 1),
                          slice=probe.get("slice", False),
                          limit=probe.get("limit", 1))
        status, got = post_placement(port, probe)
        if status != 200:
            problems.append(f"{tag}: probe {probe} -> HTTP {status}")
        elif got != want:
            problems.append(
                f"{tag}: probe {probe} diverged from the twin: "
                f"service {got} vs twin {want}")


CHANGE_ANNOTATION = "tfd.google.com/change-id"

# The crafted explain fleet: one node per taxonomy gate, changes
# stamped as annotations so the service's joins are checkable.
EXPLAIN_FLEET = {
    # The winner for gold/8 (placed; evicted later by CR deletion).
    "xa-gold-big": {agglib.PERF_CLASS: "gold", agglib.TPU_COUNT: "16",
                    agglib.SLICE_ID: "xs-1",
                    agglib.SLICE_DEGRADED: "false"},
    # insufficient-chips for chips=8 (and the best rejected node for
    # the unplaceable chips=64 counterfactual).
    "xb-gold-small": {agglib.PERF_CLASS: "gold", agglib.TPU_COUNT: "4"},
    # perf-degraded via the node's own verdict label.
    "xc-degraded": {agglib.PERF_CLASS: "degraded",
                    agglib.TPU_COUNT: "8"},
    # class-floor for gold queries.
    "xd-silver": {agglib.PERF_CLASS: "silver", agglib.TPU_COUNT: "8"},
    # lifecycle gates.
    "xe-preempt": {agglib.PERF_CLASS: "gold", agglib.TPU_COUNT: "8",
                   agglib.LIFECYCLE_PREEMPT: "true"},
    "xf-drain": {agglib.PERF_CLASS: "gold", agglib.TPU_COUNT: "8",
                 agglib.LIFECYCLE_DRAINING: "true"},
    # Worst-of-members: xg-m0's own claim blocks itself (member =
    # self) AND its healthy peer xg-m1 (member = xg-m0, change =
    # xg-m0's write).
    "xg-m0": {agglib.PERF_CLASS: "gold", agglib.TPU_COUNT: "8",
              agglib.SLICE_ID: "xs-2", agglib.SLICE_DEGRADED: "true"},
    "xg-m1": {agglib.PERF_CLASS: "gold", agglib.TPU_COUNT: "8",
              agglib.SLICE_ID: "xs-2", agglib.SLICE_DEGRADED: "false"},
}

EXPLAIN_PROBES = [
    {"class": "gold", "chips": 8, "job": "ej-placed"},
    {"class": "gold", "chips": 64, "job": "ej-unplaceable"},
    {"class": "any", "chips": 4, "slice": True, "job": "ej-slice"},
    {"class": "silver", "chips": 4, "limit": 8, "job": "ej-floor"},
]


def explain_drill(args):
    """The ISSUE 18 smoke: explained answers twin-exact with change-id
    joins, zero reads, byte-prefix pay-for-what-you-use, and the
    /v1/decisions audit ring incl. the eviction join."""
    problems = []
    with FakeApiServer() as server:
        twin = placementlib.PlacementIndex()
        for node, labels in EXPLAIN_FLEET.items():
            change = f"ch-{node}-1"
            server.seed(NS, f"tfd-features-for-{node}", labels,
                        {NODE_NAME_LABEL: node},
                        annotations={CHANGE_ANNOTATION: change})
            twin.apply_node(node, labels, change=change)

        qport, oport = free_port(), free_port()
        proc = subprocess.Popen(
            [args.binary, "--mode=placement",
             f"--placement-listen-addr=127.0.0.1:{qport}",
             f"--introspection-addr=127.0.0.1:{oport}",
             "--placement-audit-capacity=64"],
            env={**os.environ, "TFD_APISERVER_URL": server.url,
                 "KUBERNETES_NAMESPACE": NS,
                 "POD_NAME": "placement-smoke-0",
                 "GCE_METADATA_HOST": "127.0.0.1:1"},
            stderr=subprocess.DEVNULL)
        try:
            if not wait_for(
                    lambda: http_get(qport, "/readyz")[0] == 200):
                print("explain smoke FAILED: /readyz never went 200",
                      file=sys.stderr)
                return 1

            # Pay-for-what-you-use, byte for byte: the non-explain
            # answer must be a strict prefix of the explained one
            # (modulo the closing brace) — the explain section only
            # ever APPENDS to the same document.
            plain_doc = {"class": "gold", "chips": 8}
            _, plain = post_placement_raw(qport, plain_doc)
            _, plain_false = post_placement_raw(
                qport, {**plain_doc, "explain": False})
            _, explained = post_placement_raw(
                qport, {**plain_doc, "explain": True})
            if plain != plain_false:
                problems.append(
                    "explain:false changed the answer bytes vs the "
                    "key being absent")
            if "explain" in plain:
                problems.append(
                    "non-explain answer leaked an explain section")
            stem = plain.rstrip("\n").rstrip("}")
            if not explained.startswith(stem + ',"explain":'):
                problems.append(
                    "explained answer is not the non-explain bytes "
                    "plus an appended explain section: "
                    f"{plain!r} vs {explained!r}")

            # The explained battery: twin-exact, closed taxonomy,
            # zero apiserver reads.
            reads_before = len(server.requests)
            for probe in EXPLAIN_PROBES:
                want = twin.query(wanted=probe["class"],
                                  chips=probe.get("chips", 1),
                                  slice=probe.get("slice", False),
                                  limit=probe.get("limit", 1),
                                  explain=True)
                status, got = post_placement(
                    qport, {**probe, "explain": True})
                if status != 200:
                    problems.append(
                        f"explain probe {probe} -> HTTP {status}")
                    continue
                if got != want:
                    problems.append(
                        f"explain probe {probe} diverged from the "
                        f"twin: service {got} vs twin {want}")
                    continue
                bad = set(got["explain"]["reasons"]) - \
                    set(placementlib.REJECTION_REASONS)
                if bad:
                    problems.append(
                        f"explain probe {probe} used reasons outside "
                        f"the closed taxonomy: {sorted(bad)}")
            if len(server.requests) != reads_before:
                problems.append(
                    f"{len(server.requests) - reads_before} apiserver "
                    "request(s) landed DURING the explained battery — "
                    "explanations must come from the in-memory index")

            # Spot-check the pinned joins the twin equality implies:
            # the unplaceable counterfactual names the best node and
            # the change-id of the blocking write.
            _, unplaceable = post_placement(
                qport, {"class": "gold", "chips": 64, "explain": True,
                        "job": "ej-counterfactual"})
            cf = unplaceable["explain"]["counterfactual"]
            if not cf.startswith("insufficient-chips: needs 48 more "
                                 "free chip(s); best node xa-gold-big "
                                 "has 16 free"):
                problems.append(f"pinned counterfactual diverged: {cf!r}")
            if "(change ch-xa-gold-big-1)" not in cf:
                problems.append(
                    f"counterfactual lost the change-id join: {cf!r}")
            by_node = {r["node"]: r
                       for r in unplaceable["explain"]["rejections"]}
            peer = by_node.get("xg-m1", {})
            if peer.get("member") != "xg-m0" or \
                    peer.get("change") != "ch-xg-m0-1":
                problems.append(
                    "slice rejection lost the blocking-member / "
                    f"change join: {peer}")

            # The audit ring: every POST above closed a decision.
            status, body = http_get(qport, "/v1/decisions")
            ring = json.loads(body)
            if ring["capacity"] != 64:
                problems.append(
                    "--placement-audit-capacity=64 did not size the "
                    f"ring: {ring['capacity']}")
            if ring["appended"] != len(ring["decisions"]) or \
                    ring["appended"] < len(EXPLAIN_PROBES) + 4:
                problems.append(
                    f"ring did not close every decision: {ring}")
            _, body = http_get(qport, "/v1/decisions?job=ej-floor")
            only = json.loads(body)["decisions"]
            if len(only) != 1 or only[0]["job"] != "ej-floor":
                problems.append(f"?job= filter broke: {only}")
            _, body = http_get(qport, "/v1/decisions?n=1")
            tail = json.loads(body)["decisions"]
            if len(tail) != 1 or \
                    tail[0]["seq"] != ring["appended"] - 1:
                problems.append(f"?n=1 did not render the tail: {tail}")

            # Eviction join: deleting the placed node's CR closes the
            # placements naming it, carrying the retained change-id.
            server.delete(NS, "tfd-features-for-xa-gold-big")
            twin.remove_node("xa-gold-big")

            def evicted():
                _, body = http_get(
                    qport, "/v1/decisions?node=xa-gold-big")
                return any(d["outcome"] == "evicted"
                           for d in json.loads(body)["decisions"])

            if not wait_for(evicted):
                problems.append(
                    "no evicted audit entry after the node CR delete")
            else:
                _, body = http_get(
                    qport, "/v1/decisions?node=xa-gold-big")
                ev = [d for d in json.loads(body)["decisions"]
                      if d["outcome"] == "evicted"][-1]
                if ev["reason"] != "deleted" or \
                        "ej-placed" not in ev["jobs"] or \
                        ev["change_ids"] != ["ch-xa-gold-big-1"]:
                    problems.append(
                        f"evicted entry lost its joins: {ev}")
                _, metrics = http_get(oport, "/metrics")
                if metricslib.sample_value(
                        metrics, "tfd_placement_decisions_total",
                        {"outcome": "evicted"}) != 1.0:
                    problems.append(
                        "tfd_placement_decisions_total{outcome="
                        "\"evicted\"} did not count the eviction")
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)

    print(json.dumps({"explain_probes": len(EXPLAIN_PROBES) + 4,
                      "problems": problems}))
    if problems:
        for p in problems:
            print(f"explain smoke FAILED: {p}", file=sys.stderr)
        return 1
    print("explain smoke OK: explained answers twin-exact with "
          "change-id joins, zero reads, non-explain bytes a strict "
          "prefix, audit ring served with filters and the eviction "
          "join")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--binary", default="build/tpu-feature-discovery")
    ap.add_argument("--nodes", type=int, default=600)
    ap.add_argument("--churn", type=int, default=400,
                    help="label mutations to stream (sized past the "
                         "fake apiserver's DEFAULT 64-event window)")
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--explain", action="store_true",
                    help="run the ISSUE 18 explainability drill "
                         "instead of the churn smoke")
    args = ap.parse_args(argv)
    if args.explain:
        return explain_drill(args)

    rng = random.Random(args.seed)
    problems = []
    # The satellite rule of thumb under test: history depth scales with
    # the fleet, so churn bursts proportional to fleet size can never
    # push the compaction floor past a live consumer's resume point.
    depth = max(256, 2 * args.nodes)

    with FakeApiServer(collection_history=depth) as server:
        twin = placementlib.PlacementIndex()
        fleet = {}
        for i in range(args.nodes):
            node = f"sp-{i:05d}"
            labels = node_labels(rng, i)
            fleet[node] = labels
            server.seed(NS, f"tfd-features-for-{node}", labels,
                        {NODE_NAME_LABEL: node})
            twin.apply_node(node, labels)

        qport, oport = free_port(), free_port()
        proc = subprocess.Popen(
            [args.binary, "--mode=placement",
             f"--placement-listen-addr=127.0.0.1:{qport}",
             f"--introspection-addr=127.0.0.1:{oport}"],
            env={**os.environ, "TFD_APISERVER_URL": server.url,
                 "KUBERNETES_NAMESPACE": NS,
                 "POD_NAME": "placement-smoke-0",
                 "GCE_METADATA_HOST": "127.0.0.1:1"},
            stderr=subprocess.DEVNULL)
        try:
            if not wait_for(
                    lambda: http_get(qport, "/readyz")[0] == 200):
                print("placement smoke FAILED: /readyz never went 200",
                      file=sys.stderr)
                return 1
            lists_after_sync = collection_lists(server)

            probe_battery(qport, twin, problems, "post-sync")
            reads_before = len(server.requests)
            probe_battery(qport, twin, problems, "read-free")
            if len(server.requests) != reads_before:
                problems.append(
                    f"{len(server.requests) - reads_before} apiserver "
                    "request(s) landed DURING the query battery — "
                    "queries must be served from the in-memory index")

            # Churn far past the default 64-event history window.
            nodes = sorted(fleet)
            for _ in range(args.churn):
                node = rng.choice(nodes)
                fleet[node] = churn_labels(rng, fleet[node])
                server.seed(NS, f"tfd-features-for-{node}", fleet[node],
                            {NODE_NAME_LABEL: node})
                twin.apply_node(node, fleet[node])

            # Convergence: the service's event counter catches up, then
            # the battery must agree again.
            def caught_up():
                status, body = http_get(oport, "/metrics")
                if status != 200:
                    return False
                try:
                    n = metricslib.sample_value(
                        body, "tfd_placement_nodes", None)
                except ValueError:
                    return False
                if n != float(args.nodes):
                    return False
                for probe in PROBES[:2]:
                    want = twin.query(wanted=probe["class"],
                                      chips=probe.get("chips", 1),
                                      slice=probe.get("slice", False),
                                      limit=probe.get("limit", 1))
                    _, got = post_placement(qport, probe)
                    if got != want:
                        return False
                return True

            if not wait_for(caught_up):
                problems.append(
                    "service never converged with the twin after "
                    f"{args.churn} churn events")
            probe_battery(qport, twin, problems, "post-churn")

            relists = collection_lists(server) - lists_after_sync
            if relists != 0:
                problems.append(
                    f"{relists} collection relist(s) during churn — a "
                    "410 storm the proportional history depth "
                    f"({depth} events for {args.nodes} nodes) is there "
                    "to prevent")

            # Admission gate end to end: zeroed capacity refuses gold,
            # deleting the inventory object admits again.
            zeroed = {agglib.CAPACITY_PREFIX + "gold": "0",
                      agglib.CAPACITY_PREFIX + "silver": "0",
                      agglib.CAPACITY_PREFIX + "unclassed": "0"}
            server.seed(NS, OUTPUT, zeroed)
            twin.apply_inventory(zeroed)
            gold = {"class": "gold", "chips": 4}
            if not wait_for(lambda: post_placement(qport, gold)[1] ==
                            twin.query(wanted="gold", chips=4)):
                problems.append("zeroed inventory never flipped the "
                                "gold query to no-capacity")
            server.delete(NS, OUTPUT)
            twin.apply_inventory({})
            if not wait_for(lambda: post_placement(qport, gold)[1] ==
                            twin.query(wanted="gold", chips=4)):
                problems.append("deleting the inventory object never "
                                "re-admitted the gold query")
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)

    summary = {
        "nodes": args.nodes,
        "churn_events": args.churn,
        "collection_history": depth,
        "probes": len(PROBES) * 3 + 2,
        "problems": problems,
    }
    print(json.dumps(summary))
    if problems:
        for p in problems:
            print(f"placement smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(f"placement smoke OK: {args.nodes} nodes, {args.churn} churn "
          f"events through a {depth}-deep history with zero relists, "
          "service == twin on every probe")
    return 0


if __name__ == "__main__":
    sys.exit(main())

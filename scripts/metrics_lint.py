#!/usr/bin/env python3
"""CI metrics-lint: boot the daemon (null backend), scrape /metrics, and
validate the exposition with BOTH checkers — the C++ one shipped in the
unit-test binary (`tfd_unit_tests --validate-exposition`, the same
function the fuzz target uses as its oracle) and the Python twin
(tpufd.metrics.validate_exposition, the one soak's scrape parsing rides
on). Also asserts the contract metrics the deployment docs promise are
actually present, so a renamed series fails CI before it breaks
someone's dashboard.

Doc-drift gate: the metric families the booted binary actually registers
(the `# TYPE` lines of the live scrape) are diffed against the README's
Observability metric table, both directions — a scraped family missing
from the table fails as UNDOCUMENTED; a table row the binary never
registers fails as STALE (modulo CONDITIONAL: families only reachable
under configs this hermetic boot can't exercise, each annotated with the
path that emits it).

Usage:
  python3 scripts/metrics_lint.py [--binary build/tpu-feature-discovery]
      [--unit-tests build/tfd_unit_tests] [--readme README.md]

Exit 0 on a valid, complete scrape; nonzero with the reason otherwise.
"""

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from tpufd import metrics  # noqa: E402
from tpufd.fakes import free_loopback_port  # noqa: E402

# The scrape surface the docs/README promise operators. Histograms are
# checked via their _count series.
REQUIRED = [
    "tfd_rewrites_total",
    "tfd_rewrite_duration_seconds_count",
    "tfd_labeler_duration_seconds_count",
    "tfd_backend_duration_seconds_count",
    "tfd_labels_emitted",
    "tfd_last_rewrite_timestamp_seconds",
    "tfd_config_generation",
    "tfd_build_info",
    # Probe scheduler (sched/): per-source probe telemetry + the
    # degradation-ladder serving rung.
    "tfd_probe_attempts_total",
    "tfd_probe_duration_seconds_count",
    "tfd_snapshot_age_seconds",
    "tfd_probe_degradation_level",
    # Flight recorder (obs/journal): event + eviction counters, label
    # changes, and the ladder's {from,to} transition record (the first
    # pass always journals none -> <level>).
    "tfd_journal_events_total",
    "tfd_journal_dropped_total",
    "tfd_label_changes_total",
    "tfd_degradation_transitions_total",
]

# The health state machine gauges every observed source on every probe
# (healthsm/), so even this hermetic null-backend boot registers it —
# but the gauge carries a source label, so presence is asserted via the
# null source's child.
REQUIRED_LABELED = [
    ("tfd_health_state", {"source": "null"}),
    # Pass planner (ISSUE 7): the very first pass is always slow with
    # reason=first-pass (there is no published pass to short-circuit
    # against), so even this one-pass hermetic boot registers it.
    ("tfd_pass_slow_total", {"reason": "first-pass"}),
]

# Families documented in the README that this boot (null backend, no
# failures injected) legitimately never registers — each exists only on
# the named path. Anything else documented-but-unscraped is STALE.
CONDITIONAL = {
    # PJRT paths: need --backend=pjrt and a (wedged) plugin.
    "tfd_pjrt_watchdog_trips_total",
    "tfd_pjrt_cache_refreshes_total",
    # Failure paths: need an injected probe/rewrite failure.
    "tfd_probe_failures_total",
    "tfd_rewrite_failures_total",
    # Registered by the broker's backoff bookkeeping only once a worker
    # completes its first probe round — racy at scrape time.
    "tfd_probe_backoff_seconds",
    # Robustness layer (ISSUE 4): each family exists only on its path.
    # Warm restart: needs --state-file (and a restore attempt).
    "tfd_state_restores_total",
    # CR sink circuit breaker: needs --use-node-feature-api.
    "tfd_sink_breaker_state",
    "tfd_sink_breaker_transitions_total",
    # Fault injection: needs an armed --fault-spec (test runs only).
    "tfd_faults_injected_total",
    # Anti-flap layer (ISSUE 5): transitions/quarantines/suppressions
    # fire only when something actually flaps; a healthy hermetic boot
    # never does. (tfd_health_state itself is REQUIRED_LABELED above.)
    "tfd_health_transitions_total",
    "tfd_quarantines_total",
    "tfd_label_flaps_suppressed_total",
    # Hot path (ISSUE 7): a fast pass / skipped write needs a SECOND
    # pass after the first published one — racy at this boot's scrape
    # time, which stops at the first pass. (tfd_pass_slow_total is
    # REQUIRED_LABELED above: the first pass always registers it.)
    "tfd_pass_fast_total",
    "tfd_sink_writes_skipped_total",
    # Fleet-scale diff sink (ISSUE 8): the CR sink is config-gated
    # (--use-node-feature-api), so its wire counters/histogram and the
    # adaptive-backoff + anti-entropy outage records never register on
    # this file-sink boot.
    "tfd_sink_requests_total",
    "tfd_sink_patch_bytes",
    "tfd_sink_deferrals_total",
    "tfd_sink_outages_total",
    # Perf characterization (ISSUE 9): config-gated behind
    # --perf-characterize (off on this hermetic boot); restores/
    # rejections additionally need a state file carrying a perf
    # section, deferrals an exhausted duty budget, class changes a
    # re-measure that moved the debounced class.
    "tfd_perf_measures_total",
    "tfd_perf_measure_duration_seconds",
    "tfd_perf_class",
    "tfd_perf_class_changes_total",
    "tfd_perf_deferrals_total",
    "tfd_perf_restores_total",
    # Slice coherence (ISSUE 10): config-gated behind
    # --slice-coordination (off on this hermetic boot; the state gauge
    # additionally needs a derivable slice identity). Leader
    # transitions / agreement latency / orphan counts fire only on
    # live coordination events.
    "tfd_slice_state",
    "tfd_slice_leader_transitions_total",
    "tfd_slice_agreement_latency_seconds",
    "tfd_slice_orphaned_total",
    # Rejoin hysteresis (ISSUE 11 satellite): fires only when a
    # departed member rejoins a coordinated slice.
    "tfd_slice_rejoin_dwells_total",
    # Partition-tolerant fast convergence (ISSUE 19): fire only on live
    # coordination events — a stale peer answering a direct probe
    # (relay), a missed-renewal promotion (succession), and a leader
    # proxy-publishing for a relay-only member (hedge, CR sink only).
    "tfd_slice_relayed_reports_total",
    "tfd_slice_successions_total",
    "tfd_slice_hedged_publishes_total",
    # Probe-plugin SDK (ISSUE 11): config-gated behind --plugin-dir
    # (empty on this hermetic boot); failures/violations/kills
    # additionally need a misbehaving plugin.
    "tfd_plugin_state",
    "tfd_plugin_rounds_total",
    "tfd_plugin_failures_total",
    "tfd_plugin_violations_total",
    "tfd_plugin_kills_total",
    # Event-driven core (ISSUE 12): the CR watch is config-gated behind
    # --use-node-feature-api + --sink-watch (off on this file-sink
    # boot); wakeups register only once the loop parks AFTER the first
    # pass — racy at this boot's first-pass scrape.
    "tfd_sink_watch_state",
    "tfd_sink_watch_events_total",
    "tfd_sink_watch_reconnects_total",
    "tfd_pass_wakeups_total",
    # Lifecycle fast path (ISSUE 13 satellite): config-gated behind
    # --lifecycle-watch (off on this hermetic boot).
    "tfd_lifecycle_state",
    # Causal tracing (ISSUE 15): the active gauge registers at the
    # first mint (the boot's first snapshot movement) and the stage
    # histogram at the first slow pass — both usually present but racy
    # against this boot's single-pass scrape; drops need ring overflow.
    "tfd_trace_active",
    "tfd_trace_dropped_total",
    "tfd_pass_stage_duration_seconds",
    # Cluster inventory aggregator (ISSUE 13): these register only in
    # --mode=aggregator, a different runtime from this daemon boot.
    "tfd_agg_state",
    "tfd_agg_nodes",
    "tfd_agg_events_total",
    "tfd_agg_flushes_total",
    "tfd_agg_full_recomputes_total",
    "tfd_agg_flush_latency_seconds",
    # Fleet SLO engine (ISSUE 16): the burn-state gauge registers only
    # in --mode=aggregator once a stage with a budget has been seen.
    "tfd_slo_burn_state",
    # Sharded aggregation tree + placement query service (ISSUE 17):
    # the tier gauge registers in --mode=aggregator, the placement
    # families in --mode=placement — both different runtimes from this
    # daemon boot (the query histogram additionally needs a query).
    "tfd_agg_tier",
    "tfd_placement_queries_total",
    "tfd_placement_events_total",
    "tfd_placement_nodes",
    "tfd_placement_eligible_nodes",
    "tfd_placement_blocked_slices",
    "tfd_placement_query_seconds",
    # Placement decision explainability (ISSUE 18): rejections need an
    # "explain": true query, decisions/dropped need closed decisions
    # reaching the audit ring — all --mode=placement only.
    "tfd_placement_rejections_total",
    "tfd_placement_decisions_total",
    "tfd_placement_audit_dropped_total",
    # Closed-loop remediation (ISSUE 20): all --mode=remedy only — a
    # different runtime from this daemon boot. Actions/blocked/
    # rollbacks/write-failures additionally need live evidence edges.
    "tfd_remedy_state",
    "tfd_remedy_events_total",
    "tfd_remedy_cordons_active",
    "tfd_remedy_actions_total",
    "tfd_remedy_blocked_total",
    "tfd_remedy_rollbacks_total",
    "tfd_remedy_write_failures_total",
}


def readme_metric_names(readme_path):
    """Metric names promised by the README's Observability table: rows
    like `| \\`tfd_foo_total{source=}\\` | counter | ... |`."""
    import re

    names = set()
    for line in open(readme_path):
        m = re.match(r"\|\s*`(tfd_[a-zA-Z0-9_]+)", line)
        if m:
            names.add(m.group(1))
    return names


def scraped_family_names(text):
    """Families the binary actually registered: the scrape's TYPE lines
    (histograms appear under their base family name there)."""
    names = set()
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 4 and parts[0] == "#" and parts[1] == "TYPE":
            names.add(parts[2])
    return names


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--binary", default="build/tpu-feature-discovery")
    ap.add_argument("--unit-tests", default="build/tfd_unit_tests")
    ap.add_argument("--readme", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "README.md"))
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    port = free_loopback_port()

    # A real temp file, NOT /dev/null: the daemon removes its output
    # file on clean exit (stale labels must not outlive the pod), and a
    # root-run lint would otherwise delete the device node.
    import tempfile
    out_dir = tempfile.mkdtemp(prefix="tfd-metrics-lint-")
    proc = subprocess.Popen(
        [args.binary, "--sleep-interval=1s", "--backend=null",
         "--fail-on-init-error=false", "--machine-type-file=/dev/null",
         f"--output-file={os.path.join(out_dir, 'tfd')}",
         f"--introspection-addr=127.0.0.1:{port}"],
        env={**os.environ, "GCE_METADATA_HOST": "127.0.0.1:1"},
        stderr=subprocess.PIPE)
    text = None
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                print(f"daemon exited rc={proc.returncode}: "
                      f"{proc.stderr.read().decode()[-500:]}",
                      file=sys.stderr)
                return 1
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=2) as r:
                    candidate = r.read().decode()
            except OSError:
                time.sleep(0.1)
                continue
            # Wait for the first pass so the rewrite metrics exist.
            if metrics.sample_value(candidate, "tfd_rewrites_total"):
                text = candidate
                break
            time.sleep(0.1)
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    if text is None:
        print("never scraped a post-first-pass /metrics", file=sys.stderr)
        return 1

    # Checker 1: Python twin (raises on violation).
    metrics.validate_exposition(text)

    # Checker 2: the C++ checker from the unit-test binary.
    with tempfile.NamedTemporaryFile("w", suffix=".prom",
                                     delete=False) as f:
        f.write(text)
        path = f.name
    try:
        cpp = subprocess.run(
            [args.unit_tests, "--validate-exposition", path],
            capture_output=True, text=True, timeout=30)
        if cpp.returncode != 0:
            print(f"C++ checker rejected the scrape: {cpp.stderr}",
                  file=sys.stderr)
            return 1
    finally:
        os.unlink(path)

    missing = [name for name in REQUIRED
               if metrics.sample_value(text, name) is None]
    missing += [f"{name}{labels}" for name, labels in REQUIRED_LABELED
                if metrics.sample_value(text, name, labels=labels) is None]
    if missing:
        print(f"contract metrics missing from /metrics: {missing}",
              file=sys.stderr)
        return 1

    # Doc-drift gate: registered families vs the README metric table.
    documented = readme_metric_names(args.readme)
    scraped = scraped_family_names(text)
    undocumented = sorted(scraped - documented)
    stale = sorted(documented - scraped - CONDITIONAL)
    if undocumented:
        print("metrics registered by the binary but missing from the "
              f"README metric table: {undocumented}", file=sys.stderr)
        return 1
    if stale:
        print("README metric table documents series the binary never "
              f"registered (and not in CONDITIONAL): {stale}",
              file=sys.stderr)
        return 1

    print(f"metrics lint OK: {len(text.splitlines())} lines, "
          f"both checkers passed, "
          f"{len(REQUIRED) + len(REQUIRED_LABELED)} contract series "
          f"present, doc table in sync ({len(scraped)} scraped / "
          f"{len(documented)} documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/sh
# One-line version bump: rewrites EVERY versioned artifact from the new
# value so nothing can drift (the versions.mk role in the reference,
# versions.mk:17-22, where a single VERSION feeds the Makefile, CI, and
# image tags). Artifacts touched:
#   VERSION                                  (the pinned source)
#   deployments/static/*.yaml(.template)     (image tags)
#   deployments/helm/.../Chart.yaml          (version + appVersion)
#   .github/workflows/ci.yml                 (container build arg)
# tests/check-yamls.sh verifies the result; test_deployments.py runs both
# against a scratch copy so the bump flow itself is under test.
#
# Usage: set-version.sh vX.Y.Z [ROOT]
set -e

NEW=$1
ROOT=${2:-$(dirname "$0")/..}
# Strict vX.Y.Z: a glob like v[0-9]* would happily write "v1garbage" into
# VERSION, Chart.yaml and every image tag.
if ! expr "$NEW" : 'v[0-9][0-9]*\.[0-9][0-9]*\.[0-9][0-9]*$' >/dev/null; then
  echo "Usage: $0 vX.Y.Z [ROOT] (got '$NEW')" >&2
  exit 1
fi
BARE=${NEW#v}

echo "$NEW" > "$ROOT/VERSION"

for f in "$ROOT"/deployments/static/*.yaml \
         "$ROOT"/deployments/static/*.yaml.template; do
  [ -f "$f" ] || continue
  # The image-variant suffix (-full: probe runtime) is part of WHICH
  # image, not which version — preserve it across bumps. Versions are
  # strictly vX.Y.Z (gate above), so the version class needs no '-'.
  sed -i "s|tpu-feature-discovery:v[0-9][0-9a-zA-Z.+]*\(-full\)\{0,1\}|tpu-feature-discovery:${NEW}\1|g; \
          s|app.kubernetes.io/version: [0-9][0-9a-zA-Z.+-]*|app.kubernetes.io/version: ${BARE}|g" "$f"
done

# Top-level version/appVersion only: the NFD subchart pin under
# dependencies: is indented and must not be touched.
CHART="$ROOT/deployments/helm/tpu-feature-discovery/Chart.yaml"
sed -i "s|^version: \".*\"|version: \"${BARE}\"|; s|^appVersion: \".*\"|appVersion: \"${BARE}\"|" "$CHART"

CI="$ROOT/.github/workflows/ci.yml"
if [ -f "$CI" ]; then
  sed -i "s|--build-arg VERSION=v[0-9][0-9a-zA-Z.+-]*|--build-arg VERSION=${NEW}|g" "$CI"
fi

echo "version set to ${NEW}"

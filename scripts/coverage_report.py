#!/usr/bin/env python3
"""Dependency-free C++ line-coverage report over a gcov-instrumented
build (`make coverage`).

The reference computes per-package coverage in CI and excludes generated
code (its Makefile coverage target filters generated mocks); this is the
C++ equivalent built on bare `gcov --json-format --stdout` so it needs
neither gcovr nor lcov: aggregate the per-line execution counts from
every .gcda left behind by the instrumented test run, report per-file
and total line coverage for first-party sources, and enforce a floor.

Exclusions mirror the reference's generated-code filter: test code
(src/tfd/tests/), test fakes (src/tfd/testing/), and the pinned
third-party header are not product code and do not count.

Usage: coverage_report.py --build build-cov [--min PCT] [--out FILE]
"""

import argparse
import gzip
import json
import subprocess
import sys
from collections import defaultdict
from pathlib import Path

EXCLUDE_PARTS = ("src/tfd/tests/", "src/tfd/testing/", "third_party/")
INCLUDE_PARTS = ("src/", "cmd/")


def gcov_json(gcda, build_dir):
    """Runs gcov in JSON mode for one .gcda; returns parsed docs.

    gcov is pointed at the sibling .o (CMake names both
    <source>.cc.{o,gcda,gcno}): given the object file it locates its
    notes + data files itself, which the .gcda path alone does not."""
    obj = gcda.with_suffix("")  # foo.cc.gcda -> foo.cc
    obj = obj.parent / (obj.name + ".o")
    # Path relative to the gcov cwd (the build dir): gcov resolves the
    # sibling .gcno/.gcda against the path as given.
    obj = obj.resolve().relative_to(build_dir.resolve())
    proc = subprocess.run(
        ["gcov", "--json-format", "--stdout", str(obj)],
        capture_output=True, cwd=str(build_dir))
    if proc.returncode != 0:
        sys.stderr.write(f"gcov failed on {gcda}: "
                         f"{proc.stderr.decode()[:200]}\n")
        return []
    # Some gcov builds gzip even the --stdout stream: detect the magic on
    # the WHOLE buffer before any line splitting (a gzip stream contains
    # newline bytes, so splitting first would truncate it).
    raw = proc.stdout
    if raw[:2] == b"\x1f\x8b":
        try:
            raw = gzip.decompress(raw)
        except OSError:
            sys.stderr.write(f"undecompressable gcov output for {gcda}\n")
            return []
    docs = []
    for line in raw.splitlines():
        if not line.strip():
            continue
        try:
            docs.append(json.loads(line))  # one JSON doc per input
        except json.JSONDecodeError:
            continue
    return docs


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--build", type=Path, required=True)
    parser.add_argument("--min", type=float, default=0.0,
                        help="fail (exit 1) below this total line %%")
    parser.add_argument("--out", type=Path,
                        help="also write the report to this file")
    args = parser.parse_args()

    repo = Path(__file__).resolve().parent.parent
    gcdas = sorted(args.build.rglob("*.gcda"))
    if not gcdas:
        sys.stderr.write(f"no .gcda under {args.build} — build with "
                         "-DTFD_COVERAGE=ON and run the tests first\n")
        return 2

    # line number -> max count across all runs/translation units.
    per_file = defaultdict(dict)
    for gcda in gcdas:
        for doc in gcov_json(gcda, args.build):
            for f in doc.get("files", []):
                name = f.get("file", "")
                path = (args.build / name).resolve() \
                    if not Path(name).is_absolute() else Path(name)
                try:
                    rel = path.resolve().relative_to(repo).as_posix()
                except ValueError:
                    continue  # system headers
                if not rel.startswith(INCLUDE_PARTS):
                    continue
                if any(part in rel for part in EXCLUDE_PARTS):
                    continue
                lines = per_file[rel]
                for ln in f.get("lines", []):
                    n = ln.get("line_number")
                    lines[n] = max(lines.get(n, 0), ln.get("count", 0))

    rows = []
    total_lines = total_covered = 0
    for rel in sorted(per_file):
        lines = per_file[rel]
        covered = sum(1 for c in lines.values() if c > 0)
        total_lines += len(lines)
        total_covered += covered
        pct = 100.0 * covered / len(lines) if lines else 0.0
        rows.append(f"{rel:60s} {covered:5d}/{len(lines):5d} {pct:6.1f}%")
    total_pct = 100.0 * total_covered / total_lines if total_lines else 0.0
    rows.append(f"{'TOTAL':60s} {total_covered:5d}/{total_lines:5d} "
                f"{total_pct:6.1f}%")
    report = "\n".join(rows) + "\n"
    sys.stdout.write(report)
    if args.out:
        args.out.write_text(report)

    if total_pct < args.min:
        sys.stderr.write(f"FAIL: total line coverage {total_pct:.1f}% "
                         f"is below the floor {args.min:.1f}%\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Helm-less chart packaging + repo index for `make helm-package`.

The release flow (RELEASE.md step 5) produces dist/<name>-<ver>.tgz and
docs/index.yaml — the gh-pages-style chart repo surface the reference
serves from its docs/ directory. CI's tag-triggered release job uses real
helm (pinned via azure/setup-helm); this fallback produces the same two
artifacts in environments without a helm binary so the flow itself stays
runnable end-to-end everywhere:

  - the .tgz is the documented chart archive layout (a gzipped tar whose
    top-level directory is the chart name),
  - index.yaml follows the helm repo index schema (apiVersion v1,
    entries.<name>[] carrying the Chart.yaml fields plus created/digest/
    urls, digest = sha256 of the .tgz), merging any existing index so
    prior releases stay listed.

Chart dependencies: helm refuses to install an archive whose Chart.yaml
declares dependencies that are not vendored in charts/, and a packaged
.tgz cannot be `helm dependency update`d after the fact — so a dep-less
archive of this chart is NOT installable as published. The real-helm
path vendors them via `helm package --dependency-update`; this fallback
cannot fetch, so it vendors whatever charts/ (+ Chart.lock) already
holds — run `helm dependency update <chart>` first on a networked
machine — and it WARNS loudly when declared dependencies are missing
from the archive. --require-deps turns that warning into an error
(exit 1) for release pipelines.

Usage: helm_package.py --chart DIR --version X.Y.Z --dist DIR --url URL
                       [--merge INDEX] [--require-deps]
"""

import argparse
import datetime
import hashlib
import io
import re
import sys
import tarfile
from pathlib import Path

import yaml


def load_chart(chart_dir, version):
    chart = yaml.safe_load((chart_dir / "Chart.yaml").read_text())
    chart["version"] = version
    chart["appVersion"] = version
    return chart


def package(chart_dir, chart, dist):
    """Writes dist/<name>-<version>.tgz with the chart-name top dir."""
    name = chart["name"]
    out = dist / f"{name}-{chart['version']}.tgz"
    buf = io.BytesIO()
    # Rewrite Chart.yaml inside the archive with the release version so
    # the package is self-consistent even mid-bump.
    chart_yaml = yaml.safe_dump(chart, sort_keys=False).encode()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for path in sorted(chart_dir.rglob("*")):
            if path.is_dir():
                continue
            rel = path.relative_to(chart_dir)
            arcname = f"{name}/{rel}"
            if rel == Path("Chart.yaml"):
                info = tarfile.TarInfo(arcname)
                info.size = len(chart_yaml)
                tar.addfile(info, io.BytesIO(chart_yaml))
            else:
                tar.add(path, arcname=arcname)
    out.write_bytes(buf.getvalue())
    return out


def index_entry(chart, tgz, url):
    digest = hashlib.sha256(tgz.read_bytes()).hexdigest()
    created = datetime.datetime.now(datetime.timezone.utc).isoformat()
    entry = dict(chart)
    entry.update({
        "created": created,
        "digest": digest,
        "urls": [f"{url.rstrip('/')}/{tgz.name}"],
    })
    return entry


def write_index(entry, name, dist, merge):
    index = {"apiVersion": "v1", "entries": {}}
    if merge and merge.exists():
        index = yaml.safe_load(merge.read_text()) or index
        # An empty `entries:` key parses as None — setdefault won't
        # replace it.
        if not index.get("entries"):
            index["entries"] = {}
    versions = [e for e in index["entries"].get(name, [])
                if e.get("version") != entry["version"]]
    versions.insert(0, entry)
    index["entries"][name] = versions
    index["generated"] = entry["created"]
    out = dist / "index.yaml"
    out.write_text(yaml.safe_dump(index, sort_keys=False))
    return out


def missing_dependencies(chart_dir, chart):
    """Declared dependencies with no vendored archive or directory under
    charts/ — the set helm's install-time dependency check would fail on.

    Pinned exact versions must match the vendored archive name
    (helm vendors `<name>-<version>.tgz`), so a stale archive left from
    an earlier `helm dependency update` is reported instead of silently
    published; semver RANGES can't be checked by filename and fall back
    to a name-only match."""
    missing = []
    charts_dir = chart_dir / "charts"
    for dep in chart.get("dependencies") or []:
        dep_name = dep.get("name", "")
        version = str(dep.get("version", "") or "")
        # A range can be spelled with operators OR x/X wildcard segments
        # ("1.x"); only true pins map to a <name>-<version>.tgz filename.
        exact = (version and not any(c in version for c in "*^~><=| ")
                 and not re.search(r"(^|\.)[xX](\.|$)", version))
        if exact:
            archives = list(charts_dir.glob(f"{dep_name}-{version}.tgz"))
        else:
            archives = list(charts_dir.glob(f"{dep_name}-*.tgz"))
        if not archives and not (charts_dir / dep_name).is_dir():
            missing.append(f"{dep_name}-{version}" if exact else dep_name)
    return missing


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--chart", type=Path, required=True)
    parser.add_argument("--version", required=True,
                        help="bare X.Y.Z (no leading v)")
    parser.add_argument("--dist", type=Path, required=True)
    parser.add_argument("--url", required=True,
                        help="base URL the repo will be served from")
    parser.add_argument("--merge", type=Path,
                        help="existing index.yaml to keep prior releases")
    parser.add_argument("--require-deps", action="store_true",
                        help="error (exit 1) instead of warning when "
                             "declared dependencies are not vendored")
    args = parser.parse_args()

    args.dist.mkdir(parents=True, exist_ok=True)
    chart = load_chart(args.chart, args.version)
    missing = missing_dependencies(args.chart, chart)
    if missing:
        sys.stderr.write(
            "WARNING: declared dependencies not vendored in charts/: "
            f"{', '.join(missing)}. helm will REFUSE to install the "
            "packaged archive ('found in Chart.yaml, but missing in "
            "charts/ directory'); run `helm dependency update "
            f"{args.chart}` on a networked machine first, or use the "
            "real-helm release path (`helm package --dependency-update`)."
            "\n")
        if args.require_deps:
            return 1
    tgz = package(args.chart, chart, args.dist)
    entry = index_entry(chart, tgz, args.url)
    index = write_index(entry, chart["name"], args.dist, args.merge)
    print(f"packaged {tgz} (sha256 {entry['digest'][:12]}…), "
          f"index {index}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Misbehaving-plugin containment drill (ISSUE 11 acceptance).

Boots ONE real daemon (mock v2-8 backend, 1 s cadence) per phase and
walks a probe plugin through every misbehavior class the SDK promises
to contain:

  hang        sleeps past its deadline    -> process-group killed
  crash-loop  exits non-zero every round  -> backoff + flap evidence
  garbage     emits non-JSON              -> round rejected whole
  label-spam  emits > --plugin-label-budget labels -> rejected whole
  escape      writes keys outside its declared prefix -> keys dropped
  flood       writes ~10 MB to stdout     -> killed at the 1 MiB cap

Invariants asserted per misbehavior phase:
  - every OTHER source's labels are BYTE-IDENTICAL to a no-plugin
    baseline at every sampled pass (containment: the offender never
    perturbs a neighbor's labels);
  - the offender ends QUARANTINED (tfd_plugin_state == 2) with the
    evidence journaled (plugin-kill for hang/flood, plugin-violation
    for garbage/spam/escape, probe-fail for the crash loop);
  - after the plugin is FIXED, recovery is EARNED (cooldown + clean
    rounds): its labels publish and the state returns to active.

Plus the two contract proofs:
  - the ported device-health plugin (deployments/plugins/device-health)
    publishes byte-identical tpu.health.* labels to the compiled-in
    --device-health=full path given the same underlying exec;
  - the steady no-op pass p50 stays under 1 ms with two well-behaved
    plugins registered (measured from the daemon's own journal).

`--json FILE` writes the record bench_gate.py --plugin gates against
the committed BENCH_r11.json.

Usage:
  python3 scripts/plugin_soak.py [--seed 11] [--json out.json]
"""

import argparse
import json
import os
import signal
import statistics
import subprocess
import sys
import textwrap
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpufd import journal as tpufd_journal  # noqa: E402
from tpufd import metrics  # noqa: E402
from tpufd.fakes import free_loopback_port  # noqa: E402

BINARY = Path(os.environ.get("TFD_BUILD_DIR", REPO / "build")) / \
    "tpu-feature-discovery"
FIXTURE = REPO / "tests" / "fixtures" / "v2-8.yaml"
IN_TREE = REPO / "deployments" / "plugins"

# Keys that legitimately move across runs/passes. The quarantine
# annotation belongs to the OFFENDER's containment, not to a neighbor
# source, so the byte-stability check excludes it and asserts it
# separately.
VOLATILE = ("google.com/tfd.timestamp", "google.com/tpu.health.probe-ms",
            "google.com/tpu.health.quarantined")

MODES = ("hang", "crash-loop", "garbage", "label-spam", "escape", "flood")


def log(msg):
    print(f"[plugin-soak] {msg}", flush=True)


def http_get(port, path, timeout=2):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, r.read().decode()
    except OSError:
        return None, ""


def wait_for(predicate, timeout, interval=0.2, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what or predicate}")


class Daemon:
    def __init__(self, out_dir, tag, extra_argv=(), env_extra=None):
        self.port = free_loopback_port()
        self.out_file = Path(out_dir) / f"labels-{tag}"
        argv = [str(BINARY), "--sleep-interval=1s", "--backend=mock",
                "--event-driven=false",  # cadence-counted sampling
                f"--mock-topology-file={FIXTURE}",
                "--machine-type-file=/dev/null", "--no-timestamp",
                "--journal-capacity=2048",
                f"--output-file={self.out_file}",
                f"--introspection-addr=127.0.0.1:{self.port}",
                *extra_argv]
        env = {**os.environ, "GCE_METADATA_HOST": "127.0.0.1:1",
               **(env_extra or {})}
        self.proc = subprocess.Popen(argv, env=env,
                                     stderr=subprocess.DEVNULL)

    def labels(self):
        try:
            return dict(line.split("=", 1)
                        for line in self.out_file.read_text().splitlines()
                        if line)
        except (OSError, ValueError):
            return {}

    def journal(self):
        status, body = http_get(self.port, "/debug/journal?n=2048")
        if status != 200:
            return []
        try:
            return tpufd_journal.parse_journal(json.loads(body))["events"]
        except (ValueError, KeyError):
            return []

    def scrape(self, name, labels=None):
        status, text = http_get(self.port, "/metrics")
        if status != 200:
            return None
        try:
            return metrics.sample_value(text, name, labels=labels)
        except ValueError:
            return None

    def stop(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait()


def stable_view(labels):
    """A label set with volatile + plugin-owned keys removed — the
    byte-stability comparison unit."""
    return {k: v for k, v in labels.items()
            if k not in VOLATILE
            and not k.startswith("google.com/tpu.plugin.")
            and not k.startswith("google.com/tpu.health.")}


def write_chaos_plugin(plugin_dir, mode_file, budget=32):
    """One /bin/sh plugin whose behavior is switched at runtime through
    `mode_file` — discovery happens once, misbehavior and the fix need
    no SIGHUP."""
    spam_keys = ",".join(
        f'\\"google.com/tpu.plugin.chaos.k{i}\\": \\"{i}\\"'
        for i in range(budget + 8))
    path = plugin_dir / "chaos-probe"
    path.write_text(textwrap.dedent(f"""\
        #!/bin/sh
        if [ "$TFD_PLUGIN_OP" = handshake ]; then
          echo '{{"contract": "tfd.probe/v1", "name": "chaos",
                 "label_prefix": "google.com/tpu.plugin.chaos."}}'
          exit 0
        fi
        case "$(cat {mode_file})" in
          hang)       sleep 60 ;;
          crash-loop) exit 3 ;;
          garbage)    echo 'XX{{{{ not json' ;;
          label-spam) echo "{{\\"labels\\": {{{spam_keys}}}}}" ;;
          escape)     echo '{{"labels": {{
                        "google.com/tpu.plugin.chaos.ok": "true",
                        "google.com/tpu.product": "spoofed",
                        "google.com/tpu.perf.class": "gold"}}}}' ;;
          flood)      head -c 10485760 /dev/zero | tr '\\0' 'x' ;;
          *)          echo '{{"labels": {{
                        "google.com/tpu.plugin.chaos.ok": "true"}}}}' ;;
        esac
        """))
    path.chmod(0o755)
    # Deadline stanza: the hang must die in seconds, not the 30s
    # default — this is the operator-trusted knob the SDK documents.
    (plugin_dir / "chaos-probe.conf").write_text("deadline = 2s\n")
    return path


def baseline_phase(work):
    log("phase baseline: no plugins")
    daemon = Daemon(work, "baseline")
    try:
        wait_for(lambda: "google.com/tpu.count" in daemon.labels(), 30,
                 what="baseline labels")
        time.sleep(2)
        return stable_view(daemon.labels())
    finally:
        daemon.stop()


def golden_phase(work, record):
    """Device-health port golden: byte-identical exec labels vs the
    compiled-in path, same underlying exec."""
    log("phase golden: device-health port vs compiled-in")
    fake_exec = Path(work) / "fake-health"
    fake_exec.write_text(textwrap.dedent("""\
        #!/bin/sh
        echo "google.com/tpu.health.ok=true"
        echo "google.com/tpu.health.devices=$TFD_CHIP_COUNT"
        echo "google.com/tpu.health.device-0-ok=true"
        echo "google.com/tpu.health.matmul-tflops=42.5"
        """))
    fake_exec.chmod(0o755)

    def health_view(daemon):
        return {k: v for k, v in daemon.labels().items()
                if k.startswith("google.com/tpu.health.")
                and k != "google.com/tpu.health.probe-ms"}

    compiled = Daemon(work, "golden-compiled",
                      ["--device-health=full",
                       f"--health-exec={fake_exec}"])
    try:
        wait_for(lambda: "google.com/tpu.health.matmul-tflops"
                 in compiled.labels(), 30, what="compiled-in health")
        compiled_view = health_view(compiled)
    finally:
        compiled.stop()

    plugin_dir = Path(work) / "plugins-golden"
    plugin_dir.mkdir()
    port_file = plugin_dir / "device-health"
    port_file.write_text((IN_TREE / "device-health").read_text())
    port_file.chmod(0o755)
    ported = Daemon(work, "golden-ported",
                    [f"--plugin-dir={plugin_dir}"],
                    {"TFD_PLUGIN_HEALTH_EXEC": str(fake_exec)})
    try:
        wait_for(lambda: "google.com/tpu.health.matmul-tflops"
                 in ported.labels(), 30, what="ported health")
        ported_view = health_view(ported)
    finally:
        ported.stop()

    record["ported_health_golden_equal"] = ported_view == compiled_view
    assert ported_view == compiled_view, (
        f"device-health port diverged: {ported_view} != {compiled_view}")
    log(f"  golden OK ({len(ported_view)} exec labels byte-equal)")


def steady_phase(work, record):
    """Steady no-op p50 with TWO well-behaved plugins registered."""
    log("phase steady: no-op p50 with two plugins")
    plugin_dir = Path(work) / "plugins-steady"
    plugin_dir.mkdir()
    for name in ("device-health", "libtpu-caps"):
        f = plugin_dir / name
        f.write_text((IN_TREE / name).read_text())
        f.chmod(0o755)
    fake_exec = Path(work) / "fake-health"  # reuse the golden fake
    daemon = Daemon(work, "steady", [f"--plugin-dir={plugin_dir}"],
                    {"TFD_PLUGIN_HEALTH_EXEC": str(fake_exec),
                     # Hint libtpu-caps down from its default 300s so
                     # the steady window actually exercises per-tick
                     # plugin rounds (the hint floor is the 1s sleep
                     # interval).
                     "TFD_PLUGIN_LIBTPU_INTERVAL": "1"})
    try:
        wait_for(lambda: "google.com/tpu.plugin.libtpu.jax"
                 in daemon.labels()
                 and "google.com/tpu.health.ok" in daemon.labels(),
                 45, what="both plugins' labels")
        time.sleep(3)  # let the first post-settle passes go clean

        def noop_samples():
            return [float(e["fields"]["duration_us"])
                    for e in daemon.journal()
                    if e["type"] == "pass-shortcircuit"]
        before = len(noop_samples())
        wait_for(lambda: len(noop_samples()) >= before + 12, 40,
                 what="12 steady no-op passes")
        samples = noop_samples()[before:]
        record["steady_noop_p50_us"] = round(
            statistics.median(samples), 1)
        record["steady_noop_passes"] = len(samples)
        rounds = daemon.scrape("tfd_plugin_rounds_total",
                               {"plugin": "libtpu-caps"}) or 0
        record["steady_plugin_rounds"] = int(rounds)
        assert rounds >= 2, "plugins were not actually probing"
        log(f"  steady no-op p50 {record['steady_noop_p50_us']}us over "
            f"{len(samples)} passes, {int(rounds)} libtpu-caps rounds")
    finally:
        daemon.stop()


def misbehavior_phase(work, mode, baseline, record):
    log(f"phase misbehave: {mode}")
    plugin_dir = Path(work) / f"plugins-{mode}"
    plugin_dir.mkdir()
    mode_file = Path(work) / f"mode-{mode}"
    mode_file.write_text(mode)
    write_chaos_plugin(plugin_dir, mode_file)

    result = {"mode": mode, "samples": 0, "stable_samples": 0,
              "quarantined": False, "journaled": False,
              "recovered": False}
    daemon = Daemon(work, f"chaos-{mode}",
                    [f"--plugin-dir={plugin_dir}",
                     "--health-flap-window=60s",
                     "--health-flap-threshold=2",
                     "--quarantine-cooldown=2s"])
    try:
        wait_for(lambda: "google.com/tpu.count" in daemon.labels(), 30,
                 what=f"{mode}: first labels")

        def sample_stable():
            view = stable_view(daemon.labels())
            result["samples"] += 1
            if view == baseline:
                result["stable_samples"] += 1
            else:
                raise AssertionError(
                    f"{mode}: other sources' labels moved: "
                    f"{set(view.items()) ^ set(baseline.items())}")

        # Quarantine must land while every sampled pass keeps the other
        # sources byte-identical to the no-plugin baseline.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            sample_stable()
            state = daemon.scrape("tfd_plugin_state", {"plugin": "chaos"})
            if state == 2.0:
                result["quarantined"] = True
                break
            time.sleep(1.0)
        assert result["quarantined"], f"{mode}: never quarantined"

        events = daemon.journal()
        if mode in ("hang", "flood"):
            kills = [e for e in events if e["type"] == "plugin-kill"]
            reason = "deadline" if mode == "hang" else "output-flood"
            result["journaled"] = any(
                e["fields"].get("reason") == reason for e in kills)
        elif mode == "crash-loop":
            result["journaled"] = any(
                e["type"] == "probe-fail"
                and e.get("source") == "plugin.chaos" for e in events)
        else:
            kind = {"garbage": "garbage", "label-spam": "label-budget",
                    "escape": "namespace"}[mode]
            result["journaled"] = any(
                e["type"] == "plugin-violation"
                and kind in e["fields"].get("kinds", "")
                for e in events)
        assert result["journaled"], f"{mode}: containment not journaled"

        # Containment held; now FIX the plugin and earn recovery
        # (cooldown + clean rounds at the quarantine cadence).
        mode_file.write_text("good")
        wait_for(lambda: daemon.labels().get(
            "google.com/tpu.plugin.chaos.ok") == "true", 60,
            what=f"{mode}: recovery labels")
        wait_for(lambda: daemon.scrape(
            "tfd_plugin_state", {"plugin": "chaos"}) == 0.0, 20,
            what=f"{mode}: recovery state")
        result["recovered"] = True
        sample_stable()
        log(f"  {mode}: quarantined + journaled + recovered, "
            f"{result['stable_samples']}/{result['samples']} stable "
            "samples")
    finally:
        daemon.stop()
    record["modes"].append(result)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=11,
                    help="recorded for provenance; the drill is "
                         "deterministic")
    ap.add_argument("--json", metavar="FILE",
                    help="write the bench record here")
    ap.add_argument("--work-dir", default=None)
    args = ap.parse_args(argv)

    if not BINARY.exists():
        log(f"daemon binary missing at {BINARY}; build first "
            "(tests/conftest.py builds it)")
        return 2

    import tempfile
    work = args.work_dir or tempfile.mkdtemp(prefix="tfd-plugin-soak-")
    Path(work).mkdir(parents=True, exist_ok=True)
    log(f"work dir {work}")

    record = {"soak": "plugin", "seed": args.seed, "interval_s": 1,
              "modes": []}
    t0 = time.monotonic()
    baseline = baseline_phase(work)
    assert "google.com/tpu.count" in baseline
    golden_phase(work, record)
    steady_phase(work, record)
    for mode in MODES:
        misbehavior_phase(work, mode, baseline, record)

    record["duration_s"] = round(time.monotonic() - t0, 1)
    record["all_quarantined"] = all(m["quarantined"]
                                    for m in record["modes"])
    record["all_journaled"] = all(m["journaled"] for m in record["modes"])
    record["all_recovered"] = all(m["recovered"] for m in record["modes"])
    record["others_byte_stable"] = all(
        m["stable_samples"] == m["samples"] for m in record["modes"])
    record["containment_samples"] = sum(m["samples"]
                                        for m in record["modes"])

    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
    log(f"plugin soak OK: {len(record['modes'])} misbehavior classes "
        f"contained, steady no-op p50 {record['steady_noop_p50_us']}us, "
        f"{record['containment_samples']} byte-stable samples, "
        f"{record['duration_s']}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
